"""Tiered epoch-cache plane (ISSUE 3 tentpole acceptance surface).

The plane's core promises, each tested against real processes and real
files: content-fingerprint invalidation (a rewritten dataset MISSES),
size-capped LRU eviction, cross-process single-flight (one decode, every
other process hits), crash safety (a SIGKILLed writer leaves no corrupt
published entry and all residue sweeps clean), and non-blocking
degradation (a full or contended plane serves direct decodes, never
stalls an epoch).
"""

import glob
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from petastorm_tpu import make_reader
from petastorm_tpu.cache_plane import (CachePlane, PlaneCache,
                                       dataset_fingerprint, sweep_residue)
from petastorm_tpu.cache_plane.plane import (ENTRY_SUFFIX, decode_entry,
                                             encode_entry)

from test_common import create_test_dataset

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope='module')
def dataset(tmp_path_factory):
    path = tmp_path_factory.mktemp('planeds')
    return create_test_dataset('file://' + str(path), num_rows=30,
                               rows_per_rowgroup=5)


def _cache_counters(diag):
    return {k: v for k, v in diag.items() if k.startswith('cache_')}


def _read_ids(url, cache_dir, **extra):
    with make_reader(url, num_epochs=1, workers_count=2,
                     shuffle_row_groups=False, cache_type='plane',
                     cache_location=cache_dir, **extra) as reader:
        ids = sorted(int(row.id) for row in reader)
        return ids, _cache_counters(reader.diagnostics)


# -- entry codec --------------------------------------------------------------

def test_entry_roundtrip_kinds():
    import pyarrow as pa
    cols = {'a': np.arange(6, dtype=np.float32).reshape(2, 3),
            'b': np.array(['x', None], dtype=object)}
    out = decode_entry(bytes(encode_entry(cols)))
    np.testing.assert_array_equal(out['a'], cols['a'])
    assert list(out['b']) == ['x', None]

    table = pa.table({'x': [1, 2, 3]})
    assert decode_entry(bytes(encode_entry(table))).equals(table)

    assert decode_entry(bytes(encode_entry(None))) is None
    assert decode_entry(bytes(encode_entry([{'r': 1}]))) == [{'r': 1}]


def test_decoded_views_are_readonly(tmp_path):
    """Plane hits are zero-copy views over the shared mapping; an
    in-place mutation must fail loudly instead of corrupting every other
    consumer's epoch."""
    plane = CachePlane(str(tmp_path / 'p'), ram_capacity_bytes=0)
    plane.get_or_fill('k', lambda: {'a': np.arange(8)})
    hit = plane.get_or_fill('k', lambda: None)
    assert not hit['a'].flags.writeable
    with pytest.raises(ValueError):
        hit['a'][0] = 99


# -- fingerprint invalidation -------------------------------------------------

def test_fingerprint_changes_on_mtime(dataset):
    from petastorm_tpu.fs_utils import get_filesystem_and_path_or_paths
    fs, _ = get_filesystem_and_path_or_paths(dataset.url)
    files = glob.glob(dataset.path + '/*.parquet')
    assert files
    before = dataset_fingerprint(fs, files)
    future = time.time() + 10
    os.utime(files[0], (future, future))
    assert dataset_fingerprint(fs, files) != before


def test_reader_misses_after_dataset_mtime_change(tmp_path, dataset):
    """The acceptance case: a warmed plane serves hits until the dataset
    bytes change under it — then every key misses (stale entries are
    unreachable, not served)."""
    cache_dir = str(tmp_path / 'plane')
    ids1, cold = _read_ids(dataset.url, cache_dir)
    ids2, warm = _read_ids(dataset.url, cache_dir)
    assert ids1 == ids2 == list(range(30))
    assert cold['cache_misses'] == 6 and cold['cache_hits'] == 0
    assert warm['cache_hits'] == 6 and warm['cache_misses'] == 0

    future = time.time() + 10
    for f in glob.glob(dataset.path + '/*.parquet'):
        os.utime(f, (future, future))
    ids3, after = _read_ids(dataset.url, cache_dir)
    assert ids3 == ids1
    assert after['cache_misses'] == 6 and after['cache_hits'] == 0


def test_transform_identity_keys_separately(tmp_path, dataset):
    """Two readers over one plane dir with different column selections
    must not share entries (the spec token is part of the context)."""
    cache_dir = str(tmp_path / 'plane')
    _, first = _read_ids(dataset.url, cache_dir, schema_fields=['id'])
    _, second = _read_ids(dataset.url, cache_dir,
                          schema_fields=['id', 'id2'])
    assert first['cache_misses'] == 6
    assert second['cache_misses'] == 6 and second['cache_hits'] == 0


def test_spec_token_stable_across_processes_and_distinct_per_func():
    """The context must be identical in EVERY process (hash randomization
    must not leak in via set ordering or function reprs — a per-process
    context means silent 0%% cross-process hit rate) while distinct
    function bodies/callees/constants stay distinct."""
    child = (
        "import sys; sys.path.insert(0, %r)\n"
        "from petastorm_tpu.cache_plane.fingerprint import spec_token\n"
        "from petastorm_tpu.predicates import in_set, in_lambda\n"
        "print(spec_token(predicate=in_set({'cat','dog','ox','emu','bee'},"
        " 'label')),\n"
        "      spec_token(predicate=in_lambda(['label'],"
        " lambda d: d['label'] in {'a','b','c','d'})),\n"
        "      spec_token(predicate=in_lambda(['label'],"
        " lambda d: d['label'] > 3)))\n" % REPO)
    lines = set()
    for seed in ('1', '2', '3'):
        env = dict(os.environ, PYTHONHASHSEED=seed, JAX_PLATFORMS='cpu')
        out = subprocess.run([sys.executable, '-c', child], env=env,
                             capture_output=True, text=True, timeout=60)
        assert out.returncode == 0, out.stderr[-500:]
        lines.add(out.stdout.strip())
    assert len(lines) == 1, 'context differs across processes: %s' % lines
    tokens = lines.pop().split()
    assert len(set(tokens)) == 3, 'distinct predicates collided: %s' % tokens


# -- LRU eviction -------------------------------------------------------------

def test_lru_eviction_under_size_cap(tmp_path):
    plane = CachePlane(str(tmp_path / 'p'), disk_capacity_bytes=300_000,
                       ram_capacity_bytes=0)
    for i in range(10):
        plane.get_or_fill('key%d' % i,
                          lambda: {'x': np.zeros(10_000)})  # ~80 KB each
    entries = [f for f in os.listdir(plane.disk.root)
               if f.endswith(ENTRY_SUFFIX)]
    assert 0 < len(entries) < 10
    assert plane.evictions > 0
    # the newest key survived; an evicted key refills (miss, not error)
    hit = plane.get_or_fill('key9', lambda: 'EVICTED')
    assert isinstance(hit, dict), 'newest key should have survived LRU'
    calls = []
    plane.get_or_fill('key0', lambda: calls.append(1) or {'x': np.zeros(4)})
    assert calls, 'evicted key must refill via the fill function'


# -- cross-process single-flight ---------------------------------------------

_FLIGHT_CHILD = r'''
import os, sys, time
import numpy as np
sys.path.insert(0, sys.argv[4])
from petastorm_tpu.cache_plane import CachePlane

plane = CachePlane(sys.argv[1], ram_capacity_bytes=0)
marker_dir = sys.argv[2]

def fill():
    open(os.path.join(marker_dir, 'fill.%d' % os.getpid()), 'w').close()
    time.sleep(0.4)  # hold the flight long enough that peers must wait
    return {'x': np.arange(32, dtype=np.int64)}

value = plane.get_or_fill(sys.argv[3], fill)
assert np.array_equal(value['x'], np.arange(32)), value
print('HIT' if not os.path.exists(
    os.path.join(marker_dir, 'fill.%d' % os.getpid())) else 'FILLED')
'''


def test_multiprocess_get_or_fill_single_decode(tmp_path):
    """N processes race get-or-fill on ONE key: exactly one runs the fill
    function, the rest serve the published entry."""
    plane_dir, marker_dir = str(tmp_path / 'p'), str(tmp_path / 'm')
    os.makedirs(marker_dir)
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    procs = [subprocess.Popen(
        [sys.executable, '-c', _FLIGHT_CHILD, plane_dir, marker_dir,
         'shared-key', REPO], env=env, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE) for _ in range(4)]
    outs = [p.communicate(timeout=60) for p in procs]
    assert all(p.returncode == 0 for p in procs), \
        [e.decode()[-500:] for _, e in outs]
    fills = os.listdir(marker_dir)
    assert len(fills) == 1, 'expected a single decode, got %s' % fills
    verdicts = sorted(o.decode().strip() for o, _ in outs)
    assert verdicts == ['FILLED', 'HIT', 'HIT', 'HIT']


# -- crash safety -------------------------------------------------------------

_KILL_CHILD = r'''
import fcntl, os, sys, time
import numpy as np
sys.path.insert(0, sys.argv[2])
from petastorm_tpu.cache_plane import CachePlane
from petastorm_tpu.cache_plane.plane import encode_entry

plane = CachePlane(sys.argv[1])
# one good published entry that must survive the crash intact
plane.get_or_fill('survivor', lambda: {'x': np.arange(16)})
# mid-publish state in EVERY tier: a partially-written tmp file whose
# flock dies with this process (exactly what a SIGKILL inside
# Tier.store leaves behind)
blob = bytes(encode_entry({'x': np.zeros(4096)}))
for tier in [t for t in (plane.ram, plane.disk) if t is not None]:
    tmp = os.path.join(tier.root, '.tmp.%d.dead' % os.getpid())
    fd = os.open(tmp, os.O_CREAT | os.O_EXCL | os.O_WRONLY, 0o644)
    fcntl.flock(fd, fcntl.LOCK_SH | fcntl.LOCK_NB)
    os.write(fd, blob[:100])  # truncated: mid-write
    # fd stays open (and locked) until the SIGKILL
# take the single-flight lock for another key, as a wedged fill would
fcntl.flock(os.open(os.path.join(plane.disk.root,
                                 plane.digest('wedged') + '.lock'),
                    os.O_CREAT | os.O_RDWR), fcntl.LOCK_EX)
print('READY', flush=True)
time.sleep(120)
'''


def test_sigkilled_writer_sweeps_clean(tmp_path):
    """SIGKILL a writer holding mid-publish tmp files (both tiers) and a
    single-flight lock: published entries stay intact, the sweep removes
    every tmp, and the orphaned lock never blocks a live filler."""
    plane_dir = str(tmp_path / 'p')
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    child = subprocess.Popen([sys.executable, '-c', _KILL_CHILD, plane_dir,
                              REPO], env=env, stdout=subprocess.PIPE,
                             stderr=subprocess.PIPE)
    assert child.stdout.readline().strip() == b'READY', \
        child.stderr.read().decode()[-500:]
    os.kill(child.pid, signal.SIGKILL)
    child.wait(timeout=30)

    plane = CachePlane(plane_dir)  # construction sweeps both tiers
    tier_roots = [t.root for t in (plane.ram, plane.disk) if t is not None]
    leftover = sweep_residue(plane_dir)  # idempotent second sweep
    for root in tier_roots:
        tmps = [f for f in os.listdir(root) if f.startswith('.tmp.')]
        assert not tmps, 'un-swept crash residue in %s: %s' % (root, tmps)
    # the published entry survived, uncorrupted
    value = plane.get_or_fill('survivor', lambda: 'MISS')
    np.testing.assert_array_equal(value['x'], np.arange(16))
    # the dead child's exclusive lock is gone with it: a fill on that key
    # proceeds immediately (no fill_wait_s stall)
    t0 = time.monotonic()
    assert plane.get_or_fill('wedged', lambda: 'fresh') == 'fresh'
    assert time.monotonic() - t0 < 5.0
    assert isinstance(leftover, dict)


# -- degradation --------------------------------------------------------------

def test_full_plane_degrades_to_direct_decode(tmp_path):
    """A plane whose tiers cannot hold even one entry serves every call
    by direct decode — correct values, bounded time, degraded counter."""
    plane = CachePlane(str(tmp_path / 'p'), disk_capacity_bytes=64,
                       ram_capacity_bytes=0)
    t0 = time.monotonic()
    for i in range(5):
        value = plane.get_or_fill('k%d' % i, lambda i=i: {'x': np.full(4096, i)})
        assert value['x'][0] == i
    assert time.monotonic() - t0 < 5.0
    assert plane.degraded == 5
    assert not [f for f in os.listdir(plane.disk.root)
                if f.endswith(ENTRY_SUFFIX)]


def test_wedged_peer_does_not_block_past_deadline(tmp_path):
    """A LIVE peer sitting on the single-flight lock past fill_wait_s
    costs this process only the bounded wait, then it decodes directly."""
    import fcntl
    plane_dir = str(tmp_path / 'p')
    plane = CachePlane(plane_dir, fill_wait_s=0.5)
    digest = plane.digest('stuck-key')
    fd = os.open(os.path.join(plane.disk.root, digest + '.lock'),
                 os.O_CREAT | os.O_RDWR)
    fcntl.flock(fd, fcntl.LOCK_EX)  # this process wedges the key forever
    try:
        t0 = time.monotonic()
        assert plane.get_or_fill('stuck-key', lambda: 'direct') == 'direct'
        elapsed = time.monotonic() - t0
        assert 0.4 < elapsed < 5.0
        assert plane.degraded == 1
    finally:
        os.close(fd)


def test_unencodable_value_serves_uncached(tmp_path):
    plane = CachePlane(str(tmp_path / 'p'), ram_capacity_bytes=0)
    value = plane.get_or_fill('gen', lambda: (lambda: 1))  # unpicklable
    assert callable(value)
    assert plane.degraded == 1


# -- service integration ------------------------------------------------------

def test_service_warm_epoch_serves_cache_hits(tmp_path, dataset,
                                              monkeypatch):
    """Two service runs over one plane dir: run 1 decodes every piece
    exactly once (the lease is the ownership grant), run 2 serves the
    whole epoch from the plane — via the cluster tier's remote-HIT path
    (no reader constructed, ``cache_remote_hits``).  A third run under
    the cluster kill switch pins the legacy behavior bit-for-bit: the
    per-split readers run and the plane answers as ``cache_hits``."""
    from petastorm_tpu.service import (Dispatcher, ServiceConfig,
                                      ServiceDataLoader, Worker)
    plane_dir = str(tmp_path / 'svcplane')

    def run_epoch():
        config = ServiceConfig(
            dataset.url, num_consumers=1, rowgroups_per_split=2,
            lease_ttl_s=2.0, reader_kwargs={'workers_count': 2},
            cache_plane=True, cache_plane_dir=plane_dir)
        with Dispatcher(config) as dispatcher:
            worker = Worker(dispatcher.addr).start()
            try:
                loader = ServiceDataLoader(dispatcher.addr, batch_size=8,
                                           consumer=0, drop_last=False)
                ids = []
                with loader:
                    for batch in loader.iter_host_batches():
                        ids.extend(np.asarray(batch['id']).tolist())
                counters = _cache_counters(worker.diagnostics)
            finally:
                worker.stop()
                worker.join()
        return sorted(ids), counters

    ids1, cold = run_epoch()
    ids2, warm = run_epoch()
    assert ids1 == ids2 == list(range(30))
    assert cold['cache_misses'] == 6 and cold['cache_hits'] == 0
    assert cold['cache_remote_hits'] == 0
    # Warm epoch, cluster tier ON (the cache_plane default): every piece
    # streams straight from the plane without constructing a reader.
    assert warm['cache_remote_hits'] == 6
    assert warm['cache_hits'] == 0 and warm['cache_misses'] == 0
    # Kill switch: the pre-cluster path — per-split readers run and the
    # plane serves them as ordinary hits.
    monkeypatch.setenv('PETASTORM_TPU_NO_CLUSTER_CACHE', '1')
    ids3, legacy = run_epoch()
    assert ids3 == ids1
    assert legacy['cache_hits'] == 6 and legacy['cache_misses'] == 0
    assert legacy['cache_remote_hits'] == 0


def test_plane_cache_pickles_across_pool_boundary(tmp_path):
    """PlaneCache rides ProcessPool worker args; mappings/locks must not
    pickle, counters and tier config must."""
    import pickle
    cache = PlaneCache(str(tmp_path / 'p'), ram_bytes=0)
    cache.get('k', lambda: {'x': np.arange(4)})
    clone = pickle.loads(pickle.dumps(cache))
    hit = clone.get('k', lambda: 'MISS')
    np.testing.assert_array_equal(hit['x'], np.arange(4))
