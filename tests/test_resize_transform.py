"""ResizeImages: declarative resize fused into the columnar decode plane.

The single most common image transform (store at native resolution, train
at fixed resolution) expressed declaratively so the columnar fast path
keeps its zero-per-row contract instead of falling back to per-row python
(an opaque TransformSpec func forces that).  Native fused decode+resize
(`pt_decode.cc :: pt_jpeg_decode_resize_batch`) approximates the cv2
fallback within a few LSB; with the native plane disabled the columnar and
row paths are bit-identical.
"""

import numpy as np
import pytest

from petastorm_tpu import make_reader, native
from petastorm_tpu.codecs import CompressedImageCodec
from petastorm_tpu.etl.dataset_metadata import DatasetWriter
from petastorm_tpu.transform import ResizeImages, transform_schema
from petastorm_tpu.unischema import Unischema, UnischemaField

ROWS = 12
SIZES = [(48, 64), (96, 80), (32, 32), (128, 96)]  # variable source sizes
TARGET = (40, 56)


def _image(rng, h, w):
    base = np.linspace(0, 255, h * w * 3, dtype=np.float32).reshape(h, w, 3)
    jig = rng.integers(0, 50, (h // 8 + 1, w // 8 + 1, 3)) \
        .repeat(8, 0).repeat(8, 1)[:h, :w]
    return np.clip(base + jig, 0, 255).astype(np.uint8)


@pytest.fixture(scope='module')
def jpeg_dataset(tmp_path_factory):
    url = 'file://' + str(tmp_path_factory.mktemp('resizeds') / 'ds')
    schema = Unischema('VarImages', [
        UnischemaField('id', np.int64, (), None, False),
        UnischemaField('image', np.uint8, (None, None, 3),
                       CompressedImageCodec('jpeg', quality=90), False),
    ])
    rng = np.random.default_rng(3)
    with DatasetWriter(url, schema, rows_per_rowgroup=4) as w:
        for i in range(ROWS):
            h, w_ = SIZES[i % len(SIZES)]
            w.write({'id': np.int64(i), 'image': _image(rng, h, w_)})
    return url


def _read_all(url, columnar, **kw):
    spec = ResizeImages({'image': TARGET})
    with make_reader(url, transform_spec=spec, columnar_decode=columnar,
                     shuffle_row_groups=False, reader_pool_type='dummy',
                     **kw) as reader:
        if columnar:
            images, ids = [], []
            for batch in reader:
                images.extend(np.asarray(batch.image))
                ids.extend(int(i) for i in batch.id)
            return dict(zip(ids, images))
        return {int(r.id): r.image for r in reader}


def test_columnar_fused_resize_shapes_and_schema(jpeg_dataset):
    spec = ResizeImages({'image': TARGET})
    with make_reader(jpeg_dataset, transform_spec=spec, columnar_decode=True,
                     shuffle_row_groups=False,
                     reader_pool_type='dummy') as reader:
        # declared target propagates to the post-transform schema
        assert reader.schema.fields['image'].shape == TARGET + (3,)
        batches = list(reader)
    for b in batches:
        assert b.image.shape[1:] == TARGET + (3,)
        assert b.image.dtype == np.uint8
    assert sum(b.image.shape[0] for b in batches) == ROWS


def test_columnar_matches_row_path_within_tolerance(jpeg_dataset):
    """Native fused decode+resize vs the row path's cv2 decode+resize:
    same shapes, values within a few LSB (documented approximation)."""
    columnar = _read_all(jpeg_dataset, columnar=True)
    row = _read_all(jpeg_dataset, columnar=False)
    assert set(columnar) == set(row) == set(range(ROWS))
    for i in range(ROWS):
        assert columnar[i].shape == row[i].shape == TARGET + (3,)
        diff = np.abs(columnar[i].astype(np.int16) - row[i].astype(np.int16))
        assert diff.mean() < 4.0, 'row %d mean diff %.2f' % (i, diff.mean())


def test_native_disabled_paths_bit_identical(jpeg_dataset):
    """With the native plane off, the columnar fallback IS cv2
    decode+resize — bit-identical to the row path."""
    with native.disabled():
        columnar = _read_all(jpeg_dataset, columnar=True)
        row = _read_all(jpeg_dataset, columnar=False)
    for i in range(ROWS):
        np.testing.assert_array_equal(columnar[i], row[i])


def test_resize_same_size_is_pure_decode(jpeg_dataset):
    """Targets matching the stored size leave pixels untouched (memcpy
    path) — compare against a no-transform read of a fixed-size dataset."""
    # reuse one stored size as the target: rows with that size must decode
    # identically with and without the resize transform
    spec = ResizeImages({'image': (48, 64)})
    with make_reader(jpeg_dataset, transform_spec=spec, columnar_decode=True,
                     shuffle_row_groups=False,
                     reader_pool_type='dummy') as reader:
        resized = {}
        for batch in reader:
            for i, img in zip(batch.id, np.asarray(batch.image)):
                resized[int(i)] = img
    with make_reader(jpeg_dataset, shuffle_row_groups=False,
                     reader_pool_type='dummy') as reader:
        for r in reader:
            if r.image.shape[:2] == (48, 64):
                np.testing.assert_array_equal(resized[int(r.id)], r.image)


def test_dct_scaled_regime_is_antialiased_not_broken():
    """>=4x reductions engage DCT-scaled decode: textured content then
    diverges from the cv2 INTER_LINEAR fallback by design (anti-aliasing).
    Assert the native output tracks the ANTI-ALIASED reference
    (cv2 INTER_AREA) far more closely than raw INTER_LINEAR does — i.e.
    the divergence is quality, not corruption."""
    import cv2
    from petastorm_tpu.native import get_lib, jpeg_decode_resize_batch
    if get_lib() is None:
        pytest.skip('native plane unavailable')
    rng = np.random.default_rng(9)
    src = rng.integers(0, 256, (400, 400, 3), np.uint8)  # pure texture
    ok, enc = cv2.imencode('.jpg', cv2.cvtColor(src, cv2.COLOR_RGB2BGR),
                           [cv2.IMWRITE_JPEG_QUALITY, 95])
    assert ok
    dst = np.zeros((1, 48, 48, 3), np.uint8)
    assert jpeg_decode_resize_batch([enc.tobytes()], dst)
    full = cv2.cvtColor(cv2.imdecode(enc, cv2.IMREAD_COLOR), cv2.COLOR_BGR2RGB)
    area = cv2.resize(full, (48, 48), interpolation=cv2.INTER_AREA)
    linear = cv2.resize(full, (48, 48), interpolation=cv2.INTER_LINEAR)
    d_area = np.abs(dst[0].astype(np.int16) - area.astype(np.int16)).mean()
    d_linear = np.abs(dst[0].astype(np.int16) - linear.astype(np.int16)).mean()
    assert d_area < 20, d_area            # tracks the anti-aliased reference
    assert d_area < 0.6 * d_linear, (d_area, d_linear)


def test_resize_images_on_batch_reader_dataframe_path(jpeg_dataset):
    """ResizeImages' func also speaks pandas for make_batch_reader...
    via the row-dict/DataFrame dual dispatch."""
    import pandas as pd
    spec = ResizeImages({'image': TARGET})
    df = pd.DataFrame({'image': [np.zeros((10, 12, 3), np.uint8)],
                       'id': [1]})
    out = spec.func(df)
    assert out['image'][0].shape == TARGET + (3,)


def test_resize_survives_process_pool(jpeg_dataset):
    """ResizeImages pickles into ZeroMQ pool children (bound-method func +
    self-cycle) and fuses there too."""
    spec = ResizeImages({'image': TARGET})
    with make_reader(jpeg_dataset, transform_spec=spec, columnar_decode=True,
                     shuffle_row_groups=False, reader_pool_type='process',
                     workers_count=2) as reader:
        total = 0
        for batch in reader:
            assert batch.image.shape[1:] == TARGET + (3,)
            total += batch.image.shape[0]
    assert total == ROWS


def test_transform_schema_derivation(jpeg_dataset):
    schema = Unischema('S', [
        UnischemaField('image', np.uint8, (None, None, 3),
                       CompressedImageCodec('jpeg'), False),
        UnischemaField('gray', np.uint8, (None, None),
                       CompressedImageCodec('png'), False),
    ])
    out = transform_schema(schema, ResizeImages({'image': (64, 48),
                                                 'gray': (32, 32)}))
    assert out.fields['image'].shape == (64, 48, 3)
    assert out.fields['gray'].shape == (32, 32)


def test_copy_dataset_with_resize(jpeg_dataset, tmp_path):
    """petastorm-copy-dataset --resize: re-encode variable-size images at a
    fixed training resolution; the copy's schema records the static shape."""
    from petastorm_tpu.tools.copy_dataset import copy_dataset

    target = 'file://' + str(tmp_path / 'resized_copy')
    n = copy_dataset(jpeg_dataset, target, resize={'image': TARGET})
    assert n == ROWS
    with make_reader(target, shuffle_row_groups=False,
                     reader_pool_type='dummy') as reader:
        assert reader.schema.fields['image'].shape == TARGET + (3,)
        rows = list(reader)
    assert len(rows) == ROWS
    for r in rows:
        assert r.image.shape == TARGET + (3,)
    with pytest.raises(ValueError, match='resize fields'):
        copy_dataset(jpeg_dataset, 'file://' + str(tmp_path / 'x'),
                     resize={'nope': (4, 4)})


def test_copy_dataset_partitions_count(jpeg_dataset, tmp_path):
    """partitions_count (Spark parity) maps to ~N output files."""
    import glob
    from petastorm_tpu.tools.copy_dataset import copy_dataset

    target_dir = tmp_path / 'parts'
    n = copy_dataset(jpeg_dataset, 'file://' + str(target_dir),
                     partitions_count=3)
    assert n == ROWS
    files = glob.glob(str(target_dir / '*.parquet'))
    assert len(files) == 3


@pytest.fixture(scope='module')
def png_dataset(tmp_path_factory):
    url = 'file://' + str(tmp_path_factory.mktemp('resizepng') / 'ds')
    schema = Unischema('VarPng', [
        UnischemaField('id', np.int64, (), None, False),
        UnischemaField('image', np.uint8, (None, None, 3),
                       CompressedImageCodec('png'), False),
    ])
    rng = np.random.default_rng(7)
    with DatasetWriter(url, schema, rows_per_rowgroup=4) as w:
        for i in range(8):
            h, w_ = SIZES[i % len(SIZES)]
            w.write({'id': np.int64(i), 'image': _image(rng, h, w_)})
    return url


def test_png_fused_resize(png_dataset):
    """PNG columns keep the fused columnar path (full decode + shared
    native bilinear); lossless source means tight agreement with cv2."""
    spec = ResizeImages({'image': TARGET})
    with make_reader(png_dataset, transform_spec=spec, columnar_decode=True,
                     shuffle_row_groups=False,
                     reader_pool_type='dummy') as reader:
        cols = {int(i): img for b in reader
                for i, img in zip(b.id, np.asarray(b.image))}
    with make_reader(png_dataset, transform_spec=spec, columnar_decode=False,
                     shuffle_row_groups=False,
                     reader_pool_type='dummy') as reader:
        rows = {int(r.id): r.image for r in reader}
    assert set(cols) == set(rows) == set(range(8))
    for i in range(8):
        assert cols[i].shape == rows[i].shape == TARGET + (3,)
        diff = np.abs(cols[i].astype(np.int16) - rows[i].astype(np.int16))
        assert diff.max() <= 2, 'row %d max diff %d' % (i, diff.max())


def test_disk_cache_keys_include_resize_identity(jpeg_dataset, tmp_path):
    """Re-reading through the SAME local-disk cache with a DIFFERENT resize
    target must not serve stale rows at the old resolution — cached worker
    payloads are post-transform, so the key carries the transform identity
    (advisor r3, medium).  Both the per-row and columnar paths."""
    def read_shapes(target, columnar):
        spec = ResizeImages({'image': target})
        with make_reader(jpeg_dataset, transform_spec=spec,
                         columnar_decode=columnar, shuffle_row_groups=False,
                         reader_pool_type='dummy', cache_type='local-disk',
                         cache_location=str(tmp_path / 'cache'),
                         cache_size_limit=1 << 26) as reader:
            if columnar:
                return {tuple(np.asarray(b.image).shape[1:]) for b in reader}
            return {r.image.shape for r in reader}

    for columnar in (False, True):
        assert read_shapes((40, 56), columnar) == {(40, 56, 3)}
        # warm cache now holds (40, 56) rows; a new target must miss it
        assert read_shapes((24, 32), columnar) == {(24, 32, 3)}, \
            'stale cached resolution served (columnar=%s)' % columnar


def _shift_id_by_1(row):
    out = dict(row)
    out['id'] = out['id'] + 1
    return out


def _shift_id_by_2(row):
    out = dict(row)
    out['id'] = out['id'] + 2
    return out


def test_disk_cache_distinguishes_opaque_funcs(jpeg_dataset, tmp_path):
    """Two different opaque TransformSpec funcs over one cache dir get
    distinct entries (keyed by module.qualname)."""
    from petastorm_tpu.transform import TransformSpec

    def read_ids(func):
        with make_reader(jpeg_dataset, transform_spec=TransformSpec(func),
                         shuffle_row_groups=False, reader_pool_type='dummy',
                         cache_type='local-disk',
                         cache_location=str(tmp_path / 'cache'),
                         cache_size_limit=1 << 26) as reader:
            return sorted(int(r.id) for r in reader)

    assert read_ids(_shift_id_by_1) == list(range(1, ROWS + 1))
    assert read_ids(_shift_id_by_2) == list(range(2, ROWS + 2)), \
        'cache served rows transformed by a different func'


def test_wildcard_shape_resize_keeps_schema_wildcard(jpeg_dataset):
    """A fully-wildcard base field (shape=None, normalized to ()) gets NO
    (h, w) schema override — asserting 2-D would misdeclare 3-channel
    images (advisor r3, low)."""
    from petastorm_tpu.codecs import CompressedImageCodec
    from petastorm_tpu.unischema import Unischema, UnischemaField

    schema = Unischema('W', [
        UnischemaField('id', np.int64, (), None, False),
        UnischemaField('image', np.uint8, None,
                       CompressedImageCodec('png'), False),
    ])
    spec = ResizeImages({'image': (10, 12)})
    out = transform_schema(schema, spec)
    assert out.fields['image'].shape == ()  # wildcard declaration survives
