"""FSDP sharding rules over the virtual 8-device CPU mesh (conftest sets
--xla_force_host_platform_device_count=8)."""

import numpy as np
import pytest

jax = pytest.importorskip('jax')
import jax.numpy as jnp  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

from petastorm_tpu.parallel import fsdp_shardings, fsdp_size_report, make_mesh


@pytest.fixture(scope='module')
def mesh():
    return make_mesh({'data': 4, 'model': 2})


def _params():
    return {
        'dense': {'kernel': jnp.zeros((512, 256), jnp.float32),
                  'bias': jnp.zeros((256,), jnp.float32)},
        'embed': {'table': jnp.zeros((1024, 128), jnp.float32)},
        'norm': {'scale': jnp.ones((256,), jnp.float32)},
    }


def test_large_params_shard_small_stay_replicated(mesh):
    shardings = fsdp_shardings(_params(), mesh)
    assert shardings['dense']['kernel'].spec == P('data')     # 512 is largest
    assert shardings['embed']['table'].spec == P('data')
    assert shardings['dense']['bias'].spec == P()             # tiny: replicated
    assert shardings['norm']['scale'].spec == P()


def test_device_put_and_compute_under_fsdp(mesh):
    """Params placed under FSDP shardings run a jitted matmul: GSPMD inserts
    the all-gather; results match replicated execution."""
    params = _params()
    shardings = fsdp_shardings(params, mesh)
    placed = jax.tree_util.tree_map(jax.device_put, params, shardings)
    x = jnp.ones((8, 512))

    @jax.jit
    def apply(p, x):
        return x @ p['dense']['kernel'] + p['dense']['bias']

    out = apply(placed, x)
    np.testing.assert_allclose(np.asarray(out), np.zeros((8, 256)))
    kernel_shards = placed['dense']['kernel'].addressable_shards
    assert {s.data.shape for s in kernel_shards} == {(128, 256)}  # 512/4


def test_composes_with_base_spec(mesh):
    """A Megatron-style base spec keeps its axis; FSDP claims a free dim."""
    def base(path):
        name = path[-1].key if hasattr(path[-1], 'key') else ''
        return P(None, 'model') if name == 'kernel' else P()

    shardings = fsdp_shardings(_params(), mesh, base_spec_fn=base)
    assert shardings['dense']['kernel'].spec == P('data', 'model')
    assert shardings['embed']['table'].spec == P('data')


def test_base_spec_already_using_data_axis(mesh):
    """Regression: a base spec that already spends the data axis must pass
    through untouched, not produce a duplicate-axis spec."""
    shardings = fsdp_shardings(
        _params(), mesh, base_spec_fn=lambda path: P('data'))
    assert shardings['dense']['kernel'].spec == P('data')
    assert shardings['embed']['table'].spec == P('data')


def test_indivisible_dims_stay_on_base(mesh):
    params = {'odd': jnp.zeros((17, 33), jnp.float32)}  # nothing divides by 4
    shardings = fsdp_shardings(params, mesh, min_shard_elements=1)
    assert shardings['odd'].spec == P()


def test_size_report(mesh):
    params = _params()
    report = fsdp_size_report(params, fsdp_shardings(params, mesh))
    total = (512 * 256 + 256 + 1024 * 128 + 256) * 4 / 2 ** 20
    assert report['total_mb'] == pytest.approx(total, rel=1e-3)
    assert report['per_device_mb'] < report['total_mb'] / 3  # mostly sharded
    assert 0.7 < report['sharded_fraction'] < 1.0


def test_missing_axis_raises(mesh):
    with pytest.raises(ValueError, match='no axis'):
        fsdp_shardings(_params(), mesh, data_axis='nope')
