"""Adaptive out-of-order preprocessing scheduler (ISSUE 9).

Covers the regression pin that precedes the tentpole (resume-token
oldest-outstanding math under heavily out-of-order acks — the invariant
the scheduler leans on), the scheduling primitives (cost model, adaptive
dispatch policy, reorder buffer), the reader wire-through (bit-identical
delivery order + resume round-trip under ``scheduling='adaptive'``), and
the autotuner's clamp/rate-limit contract.
"""

import threading
import time

import numpy as np
import pytest

from petastorm_tpu.workers_pool import VentilatedItem
from petastorm_tpu.workers_pool import scheduling as sched
from petastorm_tpu.workers_pool.ventilator import (ConcurrentVentilator,
                                                   epoch_order)


class Sink:
    """Collects ventilated items; acks on demand, in any order."""

    def __init__(self, vent=None):
        self.items = []
        self._lock = threading.Lock()
        self.vent = vent

    def __call__(self, item):
        assert isinstance(item, VentilatedItem)
        with self._lock:
            self.items.append(item)

    def take(self):
        with self._lock:
            pending, self.items = self.items, []
        return pending

    def ack(self, pending):
        for item in pending:
            self.vent.processed_item(item.position)
        return [i.args for i in pending]


def _make(items, **kwargs):
    sink = Sink()
    vent = ConcurrentVentilator(ventilate_fn=sink, items=items, **kwargs)
    sink.vent = vent
    return vent, sink


def _drain(vent, sink, timeout=5.0):
    out = []
    deadline = time.monotonic() + timeout
    while not vent.completed():
        out.extend(sink.ack(sink.take()))
        if time.monotonic() > deadline:
            raise AssertionError('ventilator did not complete; got %d items'
                                 % len(out))
        time.sleep(0.001)
    out.extend(sink.ack(sink.take()))
    return out


def _wait_items(sink, n, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        with sink._lock:
            if len(sink.items) >= n:
                return
        time.sleep(0.002)
    raise AssertionError('never saw %d ventilated items' % n)


# -- regression pin (BEFORE the tentpole): oldest-outstanding resume math
# under heavily out-of-order acks ---------------------------------------------

def test_state_dict_oldest_outstanding_under_out_of_order_acks():
    """Acks arriving in ANY order must keep the token at the oldest
    position not fully processed — the invariant adaptive (out-of-order)
    scheduling leans on."""
    vent, sink = _make(list(range(10)), iterations=1,
                       max_ventilation_queue_size=6)
    vent.start()
    _wait_items(sink, 6)
    pending = sink.take()                       # positions 0..5 in flight
    by_pos = {i.position: i for i in pending}
    # Ack newest-first, skipping position 1: the token must pin at 1,
    # not at the count of acks.
    for pos in (5, 4, 3, 2, 0):
        vent.processed_item(pos)
    time.sleep(0.1)
    token = vent.state_dict()
    assert token['epoch'] == 0 and token['cursor'] == 1
    vent.processed_item(by_pos[1].position)
    time.sleep(0.1)
    # With 1 acked, the oldest outstanding moves to the dispatch frontier.
    token2 = vent.state_dict()
    assert token2['cursor'] >= 6
    vent.stop()


def test_post_resume_delivery_exact_after_out_of_order_acks():
    """Resume from an out-of-order-ack token: the new ventilator must
    dispatch exactly the suffix from the token position — re-reads of
    acked-but-newer positions are expected (at-least-once), losses are
    not."""
    items = list(range(12))
    vent, sink = _make(items, iterations=1, randomize_item_order=True,
                       random_seed=3, max_ventilation_queue_size=5)
    vent.start()
    _wait_items(sink, 5)
    pending = sink.take()
    # ack everything EXCEPT the oldest position
    oldest = min(i.position for i in pending)
    for item in pending:
        if item.position != oldest:
            vent.processed_item(item.position)
    token = vent.state_dict()
    vent.stop()
    assert token['cursor'] == oldest

    vent2, sink2 = _make(items, iterations=1, randomize_item_order=True,
                         random_seed=token['seed'],
                         start_epoch=token['epoch'],
                         start_cursor=token['cursor'])
    vent2.start()
    resumed = _drain(vent2, sink2)
    vent2.stop()
    full = epoch_order(items, True, 3, 0)
    assert resumed == full[oldest:]


# -- cost model ---------------------------------------------------------------

def test_cost_model_seeding_orders_before_observations():
    model = sched.PieceCostModel()
    model.seed({0: 10, 1: 1000, 2: 50})
    assert model.predict(1) > model.predict(2) > model.predict(0)
    # unknown piece ranks neutral, not extreme
    assert model.predict(99) >= 0.0


def test_cost_model_ewma_overrides_seed():
    model = sched.PieceCostModel(alpha=0.5)
    model.seed({0: 1000, 1: 1})
    for _ in range(6):
        model.observe(0, 0.001)   # "big" piece turns out cheap
        model.observe(1, 0.5)     # "small" piece turns out expensive
    assert model.predict(1) > model.predict(0)
    assert model.observations == 12
    # EWMA tracks the recent value, not the first
    before = model.predict(1)
    for _ in range(8):
        model.observe(1, 0.1)
    assert model.predict(1) < before


# -- adaptive dispatch policy -------------------------------------------------

def _dispatch_all(policy, order, base=0, start=0):
    policy.begin_epoch(order, base, start)
    seq = []
    while True:
        nxt = policy.next()
        if nxt is None:
            break
        seq.append(nxt)
    return seq


def test_adaptive_policy_dispatches_each_position_exactly_once():
    model = sched.PieceCostModel()
    model.seed({i: i for i in range(20)})
    policy = sched.AdaptiveDispatchPolicy(model, window=6)
    seq = _dispatch_all(policy, [(i, 0) for i in range(20)])
    assert sorted(p for p, _ in seq) == list(range(20))


def test_adaptive_policy_launches_predicted_slow_first_within_window():
    model = sched.PieceCostModel()
    # piece 5 is predicted 100x every other piece in the first window
    model.seed({i: (1000 if i == 5 else 10) for i in range(12)})
    policy = sched.AdaptiveDispatchPolicy(model, window=8)
    seq = _dispatch_all(policy, [(i, 0) for i in range(12)])
    # slow piece 5 dispatches first even though FIFO rank is 5
    assert seq[0][1][0] == 5


def test_adaptive_policy_uniform_costs_dispatch_in_epoch_order():
    """The degenerate-cost-model guard: when every pending piece
    predicts (near-)equal cost, nothing clears ``SLOW_FACTOR`` times
    the pending median, so dispatch stays exact epoch order — it must
    not devolve into reverse-cost order, which would pin every
    in-flight slot until its delivery turn and idle the pool."""
    model = sched.PieceCostModel()
    model.seed({i: 10.0 + 0.01 * (i % 3) for i in range(24)})
    policy = sched.AdaptiveDispatchPolicy(model, window=8)
    seq = _dispatch_all(policy, [(i, 0) for i in range(24)])
    assert [p for p, _ in seq] == list(range(24))


def test_adaptive_policy_lag_bound_forces_oldest():
    """A cheap piece cannot be overtaken by more than ``window`` later
    dispatches — the bound that keeps the reorder buffer finite."""
    model = sched.PieceCostModel()
    model.seed({i: (1 if i == 0 else 100 + i) for i in range(40)})
    window = 6
    policy = sched.AdaptiveDispatchPolicy(model, window=window)
    seq = _dispatch_all(policy, [(i, 0) for i in range(40)])
    rank_of = {pos: rank for rank, (pos, _) in enumerate(seq)}
    for pos in range(40):
        assert rank_of[pos] - pos <= 2 * window, (pos, rank_of[pos])


def test_adaptive_policy_predicts_once_per_piece_per_epoch():
    """``next()`` runs under the ventilator dispatch lock: predictions
    snapshot at ADMISSION (one ``predict`` per piece per epoch), never
    once per pending piece per dispatch — window-many locked cost-model
    reads on every dispatch would contend with the ack path."""
    class Counting(sched.PieceCostModel):
        calls = 0

        def predict(self, piece):
            Counting.calls += 1
            return super().predict(piece)

    model = Counting()
    model.seed({i: (1000 if i % 7 == 0 else 10) for i in range(30)})
    policy = sched.AdaptiveDispatchPolicy(model, window=8)
    seq = _dispatch_all(policy, [(i, 0) for i in range(30)])
    assert sorted(p for p, _ in seq) == list(range(30))
    assert Counting.calls == 30


def test_adaptive_policy_oldest_undispatched_tracks_gap():
    model = sched.PieceCostModel()
    model.seed({i: (1 if i == 0 else 50) for i in range(10)})
    policy = sched.AdaptiveDispatchPolicy(model, window=4)
    policy.begin_epoch([(i, 0) for i in range(10)], 0, 0)
    first = policy.next()
    assert first is not None
    if first[0] != 0:
        # position 0 (predicted cheap) is still pending: the resume
        # frontier must stay at 0
        assert policy.oldest_undispatched_idx() == 0


def test_adaptive_policy_resume_start_cursor():
    model = sched.PieceCostModel()
    policy = sched.AdaptiveDispatchPolicy(model, window=4)
    seq = _dispatch_all(policy, [(i, 0) for i in range(10)], base=10, start=7)
    assert sorted(p for p, _ in seq) == [17, 18, 19]


# -- reorder buffer -----------------------------------------------------------

def _flat(released):
    return [r for _, _, results in released for r in results]


def test_reorder_buffer_restores_ascending_delivery():
    buf = sched.ReorderBuffer(start_position=0)
    buf.add(2, 'c')
    assert buf.complete(2, 0.2) == []     # 0, 1 still missing
    buf.add(0, 'a')
    buf.add(1, 'b')
    assert buf.complete(1, 0.1) == []
    released = buf.complete(0, 0.05)
    assert _flat(released) == ['a', 'b', 'c']
    # each released run carries its position + decode elapsed (the
    # ack-on-delivery payload pools forward to the ventilator)
    assert [(p, e) for p, e, _ in released] == [(0, 0.05), (1, 0.1),
                                                (2, 0.2)]
    assert buf.pending_positions == 0


def test_reorder_buffer_multi_result_and_empty_positions():
    buf = sched.ReorderBuffer(start_position=4)
    buf.add(5, 'x1')
    buf.add(5, 'x2')
    assert buf.complete(5) == []
    # position 4 published nothing (predicate dropped the group)
    assert _flat(buf.complete(4)) == ['x1', 'x2']


def test_reorder_buffer_prologue_runs_before_epoch_positions():
    buf = sched.ReorderBuffer(start_position=10, prologue_count=2)
    buf.add(10, 'epoch')
    assert buf.complete(10) == []
    buf.add(-1, 'p1')
    buf.add(-2, 'p0')
    assert buf.complete(-1) == []
    assert _flat(buf.complete(-2)) == ['p0', 'p1', 'epoch']


# -- reader wire-through ------------------------------------------------------

ROWS = 96


@pytest.fixture(scope='module')
def skewed_dataset(tmp_path_factory):
    """Small dataset whose row groups have strongly skewed decode cost
    (via row width): 12 row groups x 8 rows."""
    from petastorm_tpu.codecs import NdarrayCodec
    from petastorm_tpu.etl.dataset_metadata import DatasetWriter
    from petastorm_tpu.unischema import Unischema, UnischemaField

    url = 'file://' + str(tmp_path_factory.mktemp('sched') / 'ds')
    schema = Unischema('Sched', [
        UnischemaField('idx', np.int64, (), None, False),
        UnischemaField('vec', np.float32, (None,), NdarrayCodec(), False),
    ])
    rng = np.random.default_rng(0)

    def rows():
        for i in range(ROWS):
            group = i // 8
            width = 20000 if group % 4 == 0 else 64
            yield {'idx': np.int64(i),
                   'vec': rng.standard_normal(width).astype(np.float32)}

    with DatasetWriter(url, schema, rows_per_rowgroup=8) as w:
        w.write_many(rows())
    return url


def _read_ids(url, **kwargs):
    from petastorm_tpu import make_reader
    with make_reader(url, schema_fields=['idx'], **kwargs) as reader:
        return [int(row.idx) for row in reader]


def test_adaptive_delivery_order_bit_identical(skewed_dataset):
    """Delivery order under scheduling='adaptive' (4 workers, shuffled)
    must be bit-identical to the serialized FIFO order — processing
    moves, delivery does not."""
    common = dict(shuffle_row_groups=True, seed=11, num_epochs=2)
    fifo = _read_ids(skewed_dataset, reader_pool_type='dummy',
                     scheduling='fifo', **common)
    adaptive = _read_ids(skewed_dataset, workers_count=4,
                         scheduling='adaptive', **common)
    assert adaptive == fifo


def test_adaptive_resume_token_round_trip(skewed_dataset):
    """state_dict mid-stream under adaptive scheduling resumes without
    losing a row; the delivered suffix is exactly the FIFO suffix."""
    from petastorm_tpu import make_reader
    common = dict(schema_fields=['idx'], shuffle_row_groups=True, seed=7,
                  num_epochs=1, workers_count=4, scheduling='adaptive')
    with make_reader(skewed_dataset, **common) as reader:
        assert reader.scheduling == 'adaptive'
        got = []
        for i, row in enumerate(reader):
            got.append(int(row.idx))
            if i == 29:
                break
        drained = reader.drain_in_flight()
        got.extend(int(r.idx) for r in drained)
        token = reader.state_dict()
    with make_reader(skewed_dataset, resume_state=token, **common) as r2:
        resumed = [int(row.idx) for row in r2]
    serialized = _read_ids(skewed_dataset, reader_pool_type='dummy',
                           scheduling='fifo', shuffle_row_groups=True,
                           seed=7, num_epochs=1)
    # exact: after a drain, delivered + resumed is the full epoch with no
    # loss and no duplicates (delivery is in epoch order end to end)
    assert got + resumed == serialized


def test_auto_resolves_and_kill_switch(skewed_dataset, monkeypatch):
    from petastorm_tpu import make_reader
    with make_reader(skewed_dataset, schema_fields=['idx'],
                     workers_count=4, scheduling='auto') as reader:
        assert reader.scheduling == 'adaptive'
    monkeypatch.setenv('PETASTORM_TPU_NO_ADAPTIVE_SCHED', '1')
    with make_reader(skewed_dataset, schema_fields=['idx'],
                     workers_count=4, scheduling='auto') as reader:
        assert reader.scheduling == 'fifo'
    monkeypatch.delenv('PETASTORM_TPU_NO_ADAPTIVE_SCHED')
    # tiny work lists degrade to fifo under 'auto'...
    with make_reader(skewed_dataset, schema_fields=['idx'],
                     workers_count=4, scheduling='auto',
                     piece_indices=[0, 1]) as reader:
        assert reader.scheduling == 'fifo'
    # ...and single-worker pools have nothing to reorder
    with make_reader(skewed_dataset, schema_fields=['idx'],
                     workers_count=1, scheduling='auto') as reader:
        assert reader.scheduling == 'fifo'
    with pytest.raises(ValueError):
        make_reader(skewed_dataset, scheduling='sometimes')


def test_adaptive_processpool_delivery_and_multiset(skewed_dataset):
    """The ProcessPool speaks the positioned result framing: adaptive
    delivery through real child processes stays in epoch order."""
    fifo = _read_ids(skewed_dataset, reader_pool_type='dummy',
                     scheduling='fifo', shuffle_row_groups=False,
                     num_epochs=1)
    adaptive = _read_ids(skewed_dataset, reader_pool_type='process',
                         workers_count=2, scheduling='adaptive',
                         shuffle_row_groups=False, num_epochs=1)
    assert adaptive == fifo


def test_adaptive_diagnostics_surface(skewed_dataset):
    from petastorm_tpu import make_reader
    with make_reader(skewed_dataset, schema_fields=['idx'],
                     workers_count=4, scheduling='adaptive') as reader:
        list(reader)
        d = reader.diagnostics
        assert d['scheduling'] == 'adaptive'
        assert d['reorder_pending'] == 0
    with make_reader(skewed_dataset, schema_fields=['idx'],
                     workers_count=2, scheduling='fifo') as reader:
        assert reader.diagnostics['scheduling'] == 'fifo'


# -- autotuner ----------------------------------------------------------------

class _FakeHist:
    def __init__(self, p50, p99, count=100):
        self._q = {0.5: p50, 0.99: p99}
        self.count = count

    def quantile(self, q):
        return self._q[q]


def test_autotuner_widens_on_skew_and_clamps():
    from petastorm_tpu.telemetry import MetricsRegistry
    registry = MetricsRegistry('tune')
    tuner = sched.Autotuner(registry=registry, interval_s=0.0,
                            min_observations=0)
    knobs = sched.SchedulerKnobs(window=32, max_inflight=8, prefetch=2)
    # heavy skew + decode-dominant stall: widen, deepen
    for _ in range(20):
        tuner.tune(knobs, decode=_FakeHist(0.001, 0.5),
                   host_batch=_FakeHist(0.01, 0.5),
                   device_put=_FakeHist(0.001, 0.002))
    assert knobs.window == sched.MAX_WINDOW          # clamped, not runaway
    assert knobs.max_inflight <= sched.MAX_INFLIGHT
    assert knobs.prefetch <= sched.MAX_PREFETCH
    assert registry.gauge('sched_window').value == knobs.window
    assert registry.counter('sched_adjust_total').value > 0


def test_autotuner_shrinks_toward_defaults_without_skew():
    tuner = sched.Autotuner(interval_s=0.0, min_observations=0)
    knobs = sched.SchedulerKnobs(window=sched.MAX_WINDOW,
                                 max_inflight=sched.MAX_INFLIGHT,
                                 prefetch=sched.MAX_PREFETCH)
    for _ in range(40):
        tuner.tune(knobs, decode=_FakeHist(0.01, 0.012),
                   host_batch=_FakeHist(0.001, 0.002),
                   device_put=_FakeHist(0.001, 0.002))
    assert knobs.window < sched.MAX_WINDOW
    assert knobs.prefetch == sched.MIN_PREFETCH


def test_autotuner_rate_limited():
    tuner = sched.Autotuner(interval_s=3600.0, min_observations=0)
    knobs = sched.SchedulerKnobs(window=32, max_inflight=8, prefetch=2)
    tuner.tune(knobs, decode=_FakeHist(0.001, 0.5))
    first = (knobs.window, knobs.max_inflight, knobs.prefetch)
    tuner.tune(knobs, decode=_FakeHist(0.001, 0.5))   # inside the window
    assert (knobs.window, knobs.max_inflight, knobs.prefetch) == first


class _FakeStallMonitor:
    def __init__(self, wait_time=0.0, step_time=0.0):
        self.wait_time = wait_time
        self.step_time = step_time


@pytest.mark.parametrize('attach_via', ['ctor', 'attach'])
def test_autotuner_baselines_attached_stall_monitor(attach_via):
    """A monitor attached mid-life carries lifetime totals (e.g. warmup
    stalls long resolved).  The first tuning window must be a DELTA
    from the attach point — stale history must not drive a prefetch
    doubling; a genuinely starved window after attach must."""
    monitor = _FakeStallMonitor(wait_time=100.0, step_time=1.0)
    if attach_via == 'ctor':
        tuner = sched.Autotuner(interval_s=0.0, min_observations=0,
                                stall_monitor=monitor)
    else:
        tuner = sched.Autotuner(interval_s=0.0, min_observations=0)
        tuner.attach_stall_monitor(monitor)
    knobs = sched.SchedulerKnobs(window=32, max_inflight=8, prefetch=2)
    tuner.tune(knobs)
    assert knobs.prefetch == 2      # no wait since attach: hold
    monitor.wait_time += 10.0       # consumer starved THIS window
    monitor.step_time += 1.0
    tuner.tune(knobs)
    assert knobs.prefetch == 4


def test_loader_autotune_wires_gauges(skewed_dataset):
    from petastorm_tpu import make_reader
    from petastorm_tpu.jax import DataLoader
    with make_reader(skewed_dataset, workers_count=4,
                     scheduling='adaptive', num_epochs=1,
                     shuffle_row_groups=False) as reader:
        loader = DataLoader(reader, batch_size=8, transfer=False)
        for _ in loader.iter_host_batches():
            pass
        snap = loader.metrics.snapshot()
        assert 'sched_window' in snap['gauges']
        assert 'sched_prefetch' in snap['gauges']


def test_adaptive_inflight_bound_scales_with_pool(tmp_path):
    """The adaptive in-flight bound (== worst-case reorder depth in
    COMPLETED undelivered row groups) defaults to 16x the pool, not the
    flat MAX_INFLIGHT ceiling: bare make_reader consumers have no
    autotuner to shrink it, so the memory exposure must scale with the
    decode resources the user already sized."""
    from petastorm_tpu import make_reader
    from petastorm_tpu.etl.dataset_metadata import DatasetWriter
    from petastorm_tpu.unischema import Unischema, UnischemaField

    url = 'file://' + str(tmp_path / 'many')
    schema = Unischema('Many', [
        UnischemaField('idx', np.int64, (), None, False)])
    with DatasetWriter(url, schema, rows_per_rowgroup=1) as w:
        w.write_many({'idx': np.int64(i)} for i in range(80))
    with make_reader(url, workers_count=2, scheduling='adaptive',
                     num_epochs=1) as reader:
        assert reader._ventilator.max_inflight == 32   # 16 x 2 workers
        assert sorted(int(r.idx) for r in reader) == list(range(80))


def test_loader_autotuner_rebinds_after_reader_reset(skewed_dataset):
    """reader.reset() builds a new pool/ventilator/policy/cost model;
    the loader's autotuner must rebind to the fresh instances — a tuner
    bound to the dead ones freezes (its fresh-samples gate reads the
    old cost model's frozen counter) while writing knobs into stopped
    objects."""
    from petastorm_tpu import make_reader
    from petastorm_tpu.jax import DataLoader
    with make_reader(skewed_dataset, workers_count=4,
                     scheduling='adaptive', num_epochs=1,
                     shuffle_row_groups=False) as reader:
        loader = DataLoader(reader, batch_size=8, transfer=False)
        for _ in loader.iter_host_batches():
            pass
        first = loader._tuner
        assert first is not None
        reader.reset()
        for _ in loader.iter_host_batches():
            pass
        assert loader._tuner is not first, 'tuner kept the dead ventilator'
        assert loader._tuner._cost_model is reader.cost_model


# -- ventilator condition-variable waits (satellite) --------------------------

def test_ventilator_pause_unpause_without_polling_burn():
    """pause/unpause and backpressure block on a condition variable now;
    the observable contract (bounded in-flight, prompt unpause) holds."""
    vent, sink = _make(list(range(30)), iterations=1,
                       max_ventilation_queue_size=4)
    vent.start()
    _wait_items(sink, 4)
    vent.pause()
    sink.ack(sink.take())
    time.sleep(0.1)
    assert sink.take() == []       # paused: acks must not refill
    vent.unpause()
    _wait_items(sink, 4)           # resumes promptly on the cv signal
    got = _drain(vent, sink)
    assert len(got) == 26
    vent.stop()


def test_set_max_inflight_shrink_keeps_frontier_liveness():
    """Shrinking the in-flight bound below the outstanding count while
    the delivery frontier is UNDISPATCHED (early slow pieces hold every
    slot) must overdraft one dispatch to the frontier instead of
    deadlocking — under ack-on-delivery nothing can release until the
    frontier runs, so honoring the shrunk bound would wait forever."""
    model = sched.PieceCostModel()
    model.seed({i: (100.0 if i in (4, 5, 6) else 1.0) for i in range(8)})
    policy = sched.AdaptiveDispatchPolicy(model, window=12,
                                          early_limit=None)
    sink = Sink()
    vent = ConcurrentVentilator(ventilate_fn=sink,
                                items=[(i, 0) for i in range(8)],
                                iterations=1, max_ventilation_queue_size=4,
                                dispatch_policy=policy)
    sink.vent = vent
    vent.start()
    _wait_items(sink, 4)
    got = {i.position for i in sink.take()}
    # three predicted-slow pieces early-dispatch, force-oldest fills the
    # last slot with the frontier
    assert got == {0, 4, 5, 6}
    vent.processed_item(0)       # the frontier delivers...
    vent.set_max_inflight(2)     # ...then the autotuner shrinks the bound
    # ack-on-delivery: 4/5/6 cannot ack until 1..3 deliver.  Drive
    # delivery order and require every position to arrive.
    expect = 1
    deadline = time.monotonic() + 5.0
    while expect < 8 and time.monotonic() < deadline:
        got.update(i.position for i in sink.take())
        if expect in got:
            vent.processed_item(expect)
            expect += 1
        else:
            time.sleep(0.002)
    vent.stop()
    assert expect == 8, 'dispatch deadlocked at position %d' % expect


def test_autotuner_cost_model_fallback_and_no_signal_hold():
    """Without a decode histogram (the process-pool parent never
    observes one) the tuner falls back to the cost model's ack-fed skew;
    with NO signal at all it must hold the ordering knobs, not shrink
    them toward the minimums."""
    model = sched.PieceCostModel()
    tuner = sched.Autotuner(interval_s=0.0, min_observations=0,
                            cost_model=model)
    knobs = sched.SchedulerKnobs(window=32, max_inflight=16, prefetch=2)
    tuner.tune(knobs)    # no histogram, no observations: hold
    assert (knobs.window, knobs.max_inflight) == (32, 16)
    for piece in range(16):
        model.observe(piece, 50.0 if piece == 0 else 1.0)
    for _ in range(3):
        tuner.tune(knobs)  # ack-fed skew alone must widen
    assert knobs.window > 32
    assert knobs.max_inflight > 16


def test_autotuner_inflight_shrink_floor_scales_with_pool():
    """Measured non-skew shrinks the in-flight bound only down to the
    caller's floor (the loader passes 2x the pool), never the global
    MIN_INFLIGHT: under ack-on-delivery the bound counts undelivered
    positions, so a constant floor of 4 would permanently idle all but
    4 workers of a bigger pool on uniform-cost data."""
    tuner = sched.Autotuner(interval_s=0.0, min_observations=0,
                            min_inflight=20)
    knobs = sched.SchedulerKnobs(window=64,
                                 max_inflight=sched.MAX_INFLIGHT,
                                 prefetch=2)
    for _ in range(40):
        tuner.tune(knobs, decode=_FakeHist(0.01, 0.012))
    assert knobs.max_inflight == 20


def test_autotuner_prefetch_holds_without_delivery_signal():
    """The prefetch knob obeys the same no-evidence rule as the
    ordering knobs: with no StallMonitor attached and no device_put
    histogram (pure host-side consumption), a user-set prefetch must
    hold — halving it there would claw back pipeline overlap on zero
    measurements."""
    tuner = sched.Autotuner(interval_s=0.0, min_observations=0)
    knobs = sched.SchedulerKnobs(window=32, max_inflight=16,
                                 prefetch=sched.MAX_PREFETCH)
    for _ in range(5):
        tuner.tune(knobs, decode=_FakeHist(0.01, 0.012),
                   host_batch=_FakeHist(0.01, 0.02), device_put=None)
    assert knobs.prefetch == sched.MAX_PREFETCH


def test_prior_footer_scan_capped_by_file_count(skewed_dataset,
                                                monkeypatch):
    """Past MAX_PRIOR_SCAN_FILES data files in the shard, the epoch-0
    prior must skip the per-file footer scan (one GET per file on an
    object store — it would dominate time-to-first-batch) and fall back
    to row-count weights.  A spy, not a raising sentinel: the weights
    path is best-effort (``except Exception``), so a raise would be
    swallowed and the test would pass vacuously."""
    from petastorm_tpu import make_reader
    from petastorm_tpu.etl import dataset_metadata as dm

    calls = []

    def spy(fs, paths):
        calls.append(sorted(paths))
        return {}

    monkeypatch.setattr(sched, 'MAX_PRIOR_SCAN_FILES', 0)
    monkeypatch.setattr(dm, 'read_row_group_byte_sizes', spy)
    with make_reader(skewed_dataset, workers_count=4,
                     scheduling='adaptive', num_epochs=1,
                     schema_fields=['idx']) as reader:
        ids = sorted(int(row.idx) for row in reader)
    assert not calls, 'footer scan ran past the file-count cap'
    assert ids == list(range(ROWS))


def test_loader_autotune_true_on_fifo_tunes_prefetch_only(skewed_dataset):
    """autotune=True on a FIFO reader owns prefetch and nothing else:
    binding the in-flight bound (the reorder-depth knob) would let the
    not-skewed branch throttle a FIFO pipeline below the pool size."""
    from petastorm_tpu import make_reader
    from petastorm_tpu.jax import DataLoader
    with make_reader(skewed_dataset, workers_count=4, scheduling='fifo',
                     num_epochs=1, shuffle_row_groups=False) as reader:
        loader = DataLoader(reader, batch_size=8, transfer=False,
                            autotune=True)
        for _ in loader.iter_host_batches():
            pass
        assert loader._tuner is not None
        assert set(loader._knobs._setters) == {'prefetch'}


def test_ventilator_ack_elapsed_feeds_cost_model():
    model = sched.PieceCostModel()
    policy = sched.AdaptiveDispatchPolicy(model, window=4)
    sink = Sink()
    vent = ConcurrentVentilator(ventilate_fn=sink,
                                items=[(i, 0) for i in range(8)],
                                iterations=1, dispatch_policy=policy)
    sink.vent = vent
    vent.start()
    deadline = time.monotonic() + 5.0
    acked = 0
    while acked < 8 and time.monotonic() < deadline:
        for item in sink.take():
            vent.processed_item(item.position, elapsed=0.05)
            acked += 1
        time.sleep(0.001)
    vent.stop()
    assert model.observations == 8
    assert model.predict(3) == pytest.approx(0.05)
