"""Proactive materialization plane (ISSUE 18).

Covers the tentpole surfaces — warm-then-serve with ZERO consumer
decodes, durable lease/ledger progress (attempt-intact resume),
eviction-aware admission against the cache plane's estimator, the
wire-format pre-transcode contract, the layout-rewrite job — plus the
satellite seams: the shared ``write_rows`` sink under
``tools/pack_dataset.py``, the ingest planner's gap/waste telemetry,
provenance-derived warming candidates, the dispatcher's scale-in
warming hand-off, the kill switch, and the ``materialize_kill`` chaos
scenario end to end.
"""

import os
import time

import numpy as np
import pytest

from petastorm_tpu.materialize import (MATERIALIZE_LEDGER_KIND,
                                       MaterializeController, rewrite_layout)
from petastorm_tpu.materialize.controller import derive_candidates
from petastorm_tpu.materialize.rewrite import layout_stats
from petastorm_tpu.materialize.transcode import (is_wire_entry, policy_token,
                                                 verify_wire_identity,
                                                 widen_entry, wire_entry,
                                                 wire_key)

from test_common import create_test_dataset

ROWS = 24
ROWS_PER_GROUP = 4      # -> 6 pieces


@pytest.fixture(scope='module')
def dataset(tmp_path_factory):
    path = tmp_path_factory.mktemp('matds')
    return create_test_dataset('file://' + str(path), num_rows=ROWS,
                               rows_per_rowgroup=ROWS_PER_GROUP)


def _read_columns(url, plane_dir=None, schema_fields=None):
    """One first epoch through the consumer decode path
    (``columnar_decode=True`` readers share the controller's piece cache
    keys); returns (column dict keyed by id, plane diagnostics)."""
    from petastorm_tpu import make_reader
    kwargs = {}
    if plane_dir is not None:
        kwargs.update(cache_type='plane', cache_location=plane_dir)
    if schema_fields is not None:
        kwargs['schema_fields'] = schema_fields
    cols = {}
    with make_reader(url, num_epochs=1, shuffle_row_groups=False,
                     workers_count=2, columnar_decode=True,
                     **kwargs) as reader:
        for batch in reader:
            d = batch._asdict()
            for i, row_id in enumerate(np.asarray(d['id'])):
                cols[int(row_id)] = {k: np.asarray(v)[i]
                                     for k, v in d.items()}
        diag = dict(reader.diagnostics)
    return cols, diag


# -- tentpole: warm once, first epoch serves with zero decodes ---------------

def test_warm_first_epoch_serves_without_decodes(dataset, tmp_path):
    plane = str(tmp_path / 'plane')
    with MaterializeController(dataset.url, plane) as controller:
        summary = controller.run()
    assert summary['ok'], summary
    assert summary['total_pieces'] == ROWS // ROWS_PER_GROUP
    assert summary['done'] == summary['total_pieces']
    assert not summary['failed_pieces']
    assert summary['published_bytes'] > 0

    served, diag = _read_columns(dataset.url, plane_dir=plane)
    # The whole first epoch rode the warmed plane: no consumer decode.
    assert diag.get('cache_misses') == 0, diag
    assert diag.get('cache_hits') >= summary['total_pieces'], diag

    # Warming changes WHEN rows decode, never WHAT is delivered.
    truth, _ = _read_columns(dataset.url)
    assert sorted(served) == sorted(truth) == list(range(ROWS))
    for row_id in truth:
        for field in ('matrix', 'embedding', 'image_png'):
            np.testing.assert_array_equal(served[row_id][field],
                                          truth[row_id][field])


def test_ledger_resume_is_attempt_intact(dataset, tmp_path):
    plane = str(tmp_path / 'plane')
    ledger = str(tmp_path / 'ledger.json')
    with MaterializeController(dataset.url, plane,
                               ledger_path=ledger) as controller:
        first = controller.run(max_pieces=2)
    assert first['done'] == 2 and first['pending'] == 4

    # A restarted controller restores done pieces from the ledger —
    # never re-decoded — and finishes only the remainder.
    with MaterializeController(dataset.url, plane,
                               ledger_path=ledger) as controller:
        assert controller.resumed_pieces == 2
        second = controller.run()
    assert second['resumed'] == 2
    assert second['done'] == second['total_pieces']
    assert second['warmed'] == second['total_pieces'] - 2
    assert not second['failed_pieces']


def test_foreign_ledger_cold_starts(dataset, tmp_path):
    """A ledger written under a different identity/geometry must cold
    start, never lie about progress."""
    from petastorm_tpu.service.ledger import DispatcherLedger
    ledger = str(tmp_path / 'ledger.json')
    foreign = DispatcherLedger(ledger, kind=MATERIALIZE_LEDGER_KIND)
    assert foreign.acquire()
    foreign.save({'context': 'not-this-dataset',
                  'dataset_url': 'file:///elsewhere',
                  'splits': [[2, 1]] * 99})
    foreign.release()
    with MaterializeController(dataset.url, str(tmp_path / 'plane'),
                               ledger_path=ledger) as controller:
        assert controller.resumed_pieces == 0
        assert controller.run()['done'] == ROWS // ROWS_PER_GROUP


def test_kill_switch_disables_every_entry_point(dataset, tmp_path,
                                                monkeypatch):
    monkeypatch.setenv('PETASTORM_TPU_NO_MATERIALIZE', '1')
    with MaterializeController(dataset.url,
                               str(tmp_path / 'plane')) as controller:
        assert controller.run() == {'ok': False, 'reason': 'kill_switch'}
        assert controller.lease('w0', n=6) == []
        assert controller.offer_drain_candidate('w0') is False
    assert not list((tmp_path / 'plane').glob('*.cpe'))


# -- lease protocol ----------------------------------------------------------

def test_lease_expiry_requeues_and_ceiling_poisons(dataset, tmp_path):
    with MaterializeController(dataset.url, str(tmp_path / 'plane'),
                               lease_ttl_s=0.05,
                               max_piece_attempts=2) as controller:
        total = controller.summary()['total_pieces']
        g1 = controller.lease('w1', n=2)
        g2 = controller.lease('w2', n=total)
        assert len(g1) == 2 and len(g2) == total - 2
        assert set(g1).isdisjoint(g2)     # a leased piece never double-grants
        assert controller.lease('w3', n=total) == []

        time.sleep(0.1)                   # every lease expires -> requeue
        g3 = controller.lease('w3', n=total)
        assert sorted(g3) == list(range(total))   # attempt 2, last grant
        for index in g3:
            controller.release('w3', index)       # burn: crashing pieces
        # Attempt ceiling reached: pieces poison to failed, not re-grant.
        assert controller.lease('w4', n=total) == []
        assert controller.summary()['failed_pieces'] == total


def test_release_without_burn_refunds_the_attempt(dataset, tmp_path):
    with MaterializeController(dataset.url,
                               str(tmp_path / 'plane')) as controller:
        (index,) = controller.lease('w1', n=1)
        assert controller._piece_state[index][1] == 1
        controller.release('w1', index, burn_attempt=False)
        assert controller._piece_state[index][1] == 0
        assert controller.summary()['pending'] == \
            controller.summary()['total_pieces']


# -- eviction-aware admission ------------------------------------------------

def test_admit_publish_refuses_hot_victims(tmp_path):
    from petastorm_tpu.cache_plane.plane import CachePlane
    plane = CachePlane(str(tmp_path / 'plane'), disk_capacity_bytes=8192,
                       ram_capacity_bytes=0)
    assert plane.publish_blob(plane.digest('resident'), b'x' * 2048)

    est = plane.disk.eviction_estimate(1024)
    assert est['fits'] and est['victims'] == 0 and est['total_bytes'] == 2048
    est = plane.disk.eviction_estimate(16384)
    assert not est['fits'] and est['victims'] == 1
    assert est['victim_bytes'] == 2048
    assert est['victim_newest_age_s'] is not None

    admitted, est = plane.admit_publish(1024)
    assert admitted and est['fits']           # fits without eviction
    admitted, _ = plane.admit_publish(16384, hot_window_s=300.0)
    assert not admitted                       # victim accessed just now
    admitted, _ = plane.admit_publish(16384, hot_window_s=0.0)
    assert admitted                           # zero window: nothing is hot

    # Age the resident past the hot window: now it is fair game.
    (entry,) = [os.path.join(plane.disk.root, n)
                for n in os.listdir(plane.disk.root) if n.endswith('.cpe')]
    old = time.time() - 1000.0
    os.utime(entry, (old, old))
    admitted, est = plane.admit_publish(16384, hot_window_s=300.0)
    assert admitted and est['victim_newest_age_s'] >= 300.0


def test_controller_admission_refusal_is_attempt_intact(dataset, tmp_path):
    """Warming never evicts entries hotter than what it publishes: with
    a hot resident filling a tiny plane, every piece is refused, released
    attempt-intact, and retried on a later (cooler) run."""
    plane_dir = str(tmp_path / 'plane')
    # Capacity fits the hot resident exactly (the tier refuses stores
    # past capacity - 4096), so every ~8 KiB piece entry needs eviction.
    with MaterializeController(dataset.url, plane_dir,
                               cache_plane_disk_bytes=28672) as controller:
        plane = controller.identity.plane
        assert plane.publish_blob(plane.digest('hot-resident'), b'x' * 24576)
        summary = controller.run()
        assert summary['done'] == 0
        assert summary['admission_refused'] >= 1
        assert summary['pending'] == summary['total_pieces']
        assert not summary['failed_pieces']
        assert all(rec[1] == 0 for rec in controller._piece_state)
        # The hot resident survived the whole pass untouched.
        assert plane.has_digest(plane.digest('hot-resident'))


# -- wire-format pre-transcode (ISSUE 18b) -----------------------------------

def test_wire_entry_roundtrip_and_identity():
    cols = {'x': np.arange(12, dtype=np.float32).reshape(3, 4),
            'i': np.arange(3, dtype=np.int64)}
    entry = wire_entry(cols)
    assert is_wire_entry(entry)
    assert entry['policy'] == policy_token('auto')
    widened = widen_entry(entry)
    assert widened['x'].dtype == np.float32
    np.testing.assert_array_equal(widened['x'], cols['x'])
    # The PR 17 contract: host widen == jitted widen of the same narrow.
    assert verify_wire_identity(cols, entry)


def test_wire_entry_degrades_to_none():
    # Narrowing nothing: a wire copy identical to the raw entry would
    # only burn plane capacity.
    assert wire_entry({'u': np.zeros(4, np.uint8),
                       'i': np.arange(4, dtype=np.int32)}) is None
    assert wire_entry({}) is None
    assert wire_entry([1, 2]) is None
    assert wire_entry({'s': np.array(['a', 'b'], dtype=object)}) is None
    assert not is_wire_entry({'columns': {}})


def test_wire_key_and_policy_token_stability():
    assert wire_key('piece:0', 'auto') == 'piece:0:w{auto}'
    tok = policy_token({'x': 'float16', 'y': np.float32})
    assert tok == policy_token({'y': np.float32, 'x': 'float16'})
    assert policy_token(None) == 'none'


def test_controller_publishes_wire_siblings_for_numeric_views(dataset,
                                                              tmp_path):
    """A float-bearing schema view gets a second, already-narrowed entry
    per piece; the widened sibling matches the raw entry exactly."""
    from petastorm_tpu.cache_plane.plane import MISS
    from petastorm_tpu.materialize.controller import wire_digests
    fields = ['id', 'matrix', 'embedding']
    with MaterializeController(
            dataset.url, str(tmp_path / 'plane'),
            reader_kwargs={'schema_fields': fields}) as controller:
        summary = controller.run()
        assert summary['done'] == summary['total_pieces']
        assert summary['wire_published'] == summary['total_pieces']
        identity = controller.identity
        for index in range(identity.num_pieces):
            (wire_digest,) = wire_digests(identity, index)
            wire = identity.plane.lookup_digest(wire_digest)
            raw = identity.plane.lookup_digest(
                identity.piece_digests(index)[0])
            assert wire is not MISS and is_wire_entry(wire)
            widened = widen_entry(wire)
            for name in ('matrix', 'embedding'):
                narrow_dtype = wire['columns'][name].dtype
                assert narrow_dtype != raw[name].dtype  # actually narrowed
                # The sibling IS narrow(raw), and widen restores the
                # canonical output dtype (bf16 is lossy; the contract is
                # widen(narrow(rows)) on BOTH paths, not raw identity).
                np.testing.assert_array_equal(
                    wire['columns'][name], raw[name].astype(narrow_dtype))
                np.testing.assert_array_equal(
                    widened[name],
                    raw[name].astype(narrow_dtype)
                    .astype(widened[name].dtype))


def test_full_schema_skips_wire_sibling(dataset, tmp_path):
    """String columns can't ride the wire: the raw entry covers the
    serve and the skip is counted, never an error."""
    with MaterializeController(dataset.url,
                               str(tmp_path / 'plane')) as controller:
        summary = controller.run()
    assert summary['done'] == summary['total_pieces']
    assert summary['wire_published'] == 0


# -- layout rewrite (ISSUE 18c) + shared pack sink ---------------------------

def test_rewrite_layout_drives_waste_down_and_preserves_rows(dataset,
                                                             tmp_path):
    # 'id' and 'matrix' are separated by unselected columns ('id2' and
    # the PNG images — parquet chunks follow the Unischema's sorted
    # field order): the planner's merge gap rides over them -> waste.
    columns = ('id', 'matrix')
    out_url = 'file://' + str(tmp_path / 'resharded')
    summary = rewrite_layout(dataset.url, out_url, rows_per_rowgroup=8,
                             columns=columns)
    assert summary['rows'] == ROWS
    assert summary['before']['waste_bytes'] > 0
    assert summary['after']['waste_bytes'] < summary['before']['waste_bytes']
    assert summary['waste_bytes_saved'] > 0
    assert summary['after']['rows_per_row_group']['max'] <= 8
    # Offline stats and the summary are the same arithmetic.
    assert layout_stats(out_url, columns=list(columns)) == summary['after']

    # The rewrite changed layout, never data.
    from petastorm_tpu import make_reader
    with make_reader(out_url, num_epochs=1, shuffle_row_groups=False) as r:
        got = {int(row.id): np.asarray(row.matrix) for row in r}
    assert sorted(got) == list(range(ROWS))
    for row in dataset.data:
        np.testing.assert_array_equal(got[int(row['id'])], row['matrix'])

    with pytest.raises(ValueError, match='overwrite'):
        rewrite_layout(dataset.url, out_url, rows_per_rowgroup=8,
                       columns=columns)


def test_pack_dataset_writes_through_the_shared_sink(tmp_path, monkeypatch):
    """tools/pack_dataset.py and rewrite_layout share ONE writer path
    (``materialize.rewrite.write_rows``) — byte-identical layout logic,
    one configuration surface."""
    from petastorm_tpu.codecs import NdarrayCodec
    from petastorm_tpu.etl.dataset_metadata import write_dataset
    from petastorm_tpu.materialize import rewrite
    from petastorm_tpu.tools.pack_dataset import pack_dataset
    from petastorm_tpu.unischema import Unischema, UnischemaField

    src = 'file://' + str(tmp_path / 'docs')
    schema = Unischema('Docs', [
        UnischemaField('tokens', np.int32, (None,), NdarrayCodec(), False)])
    rng = np.random.default_rng(5)
    write_dataset(schema, [{'tokens': rng.integers(1, 90, 7)
                            .astype(np.int32)} for _ in range(12)],
                  src, rows_per_rowgroup=4)

    calls = []
    real_write_rows = rewrite.write_rows

    def spy(*args, **kwargs):
        calls.append((args, kwargs))
        return real_write_rows(*args, **kwargs)

    monkeypatch.setattr(rewrite, 'write_rows', spy)
    stats = pack_dataset(src, 'file://' + str(tmp_path / 'packed'),
                         field='tokens', max_len=16, rows_per_batch=4)
    assert len(calls) == 1
    assert stats['sequences_in'] == 12


# -- ingest planner gap/waste telemetry (satellite 2) ------------------------

def test_plan_stats_arithmetic():
    from petastorm_tpu.ingest.planner import plan_stats
    stats = plan_stats([(0, 10), (100, 10)], [(0, 110)])
    assert stats == {'needed_bytes': 20, 'fetched_bytes': 110,
                     'waste_bytes': 90, 'requests': 1, 'waste_pct': 81.82}
    assert plan_stats([], [])['waste_pct'] == 0.0
    # Coalescing can never report negative waste.
    assert plan_stats([(0, 10)], [(0, 10)])['waste_bytes'] == 0


def test_ingest_plane_registers_plan_waste_telemetry(tmp_path):
    import fsspec
    import pyarrow as pa
    import pyarrow.parquet as pq
    from types import SimpleNamespace

    from petastorm_tpu.ingest import IngestPlane

    # Incompressible payload so the file outgrows the footer tail and a
    # real ranged fetch (with a real plan) happens; the unselected
    # 'label' chunk between 'idx' and 'payload' is the merge-gap waste.
    path = str(tmp_path / 'probe.parquet')
    rng = np.random.default_rng(0)
    pq.write_table(pa.table({
        'idx': pa.array(np.arange(64, dtype=np.int64)),
        'label': pa.array(np.arange(64, dtype=np.int32)),
        'payload': pa.array([rng.integers(0, 256, 8192)
                             .astype(np.uint8).tobytes()
                             for _ in range(64)], type=pa.binary()),
    }), path, row_group_size=32)

    plane = IngestPlane(fsspec.filesystem('file'),
                        [SimpleNamespace(path=path, row_group=0)],
                        columns={'idx', 'payload'}, fetch_threads=1)
    try:
        plane.observe_dispatch((0,))
        assert plane.checkout(path, 0) is not None
        stats = plane.stats
        needed = stats['ingest_plan_needed_bytes']
        waste = stats['ingest_plan_waste_bytes']
        assert needed > 0
        assert waste > 0        # the 'label' chunk rode along
        assert stats['ingest_plan_waste_pct'] == pytest.approx(
            100.0 * waste / (needed + waste), abs=0.01)
    finally:
        plane.close()


# -- provenance-derived warming candidates -----------------------------------

def test_derive_candidates_ranks_cold_roots():
    class _Journal(object):
        def __init__(self, records):
            self._records = records

        def records(self):
            return self._records

    def record(root, cache, tenant=None, row_groups=(0,)):
        return {'cache': cache, 'tenant': tenant,
                'pieces': [{'path': root + '/part0.parquet',
                            'row_group': rg} for rg in row_groups]}

    journals = [_Journal([
        record('/data/hot', 'decode', tenant='t1', row_groups=(0, 1)),
        record('/data/hot', 'degraded', tenant='t2'),
        record('/data/mild', 'decode'),
        record('/data/mild', 'plane'),
        record('/data/cached', 'plane'),     # zero cold -> dropped
    ]), _Journal([record('/data/hot', 'decode', tenant='t1')])]

    candidates = derive_candidates(journals=journals)
    assert [c['root'] for c in candidates] == ['/data/hot', '/data/mild']
    hot = candidates[0]
    assert hot['cold'] == 3 and hot['records'] == 3
    assert hot['pieces'] == 2                # (path, rg 0) and (path, rg 1)
    assert hot['tenants'] == {'t1': 2, 't2': 1}

    class _Broken(object):
        def records(self):
            raise RuntimeError('torn journal')

    assert derive_candidates(journals=[_Broken()]) == []
    assert derive_candidates(journals=journals, top_k=1) == [hot]


# -- autoscaler hand-off: scale-in victims warm before they drain ------------

def test_dispatcher_defers_drain_until_warming_pass_done(dataset, tmp_path):
    from petastorm_tpu.service import Dispatcher, ServiceConfig
    config = ServiceConfig(dataset.url, num_consumers=1,
                           rowgroups_per_split=2, lease_ttl_s=2.0)
    dispatcher = Dispatcher(config, num_pieces=2)  # no serve thread needed
    w0 = dispatcher._op_register_worker({'data_addr': 'tcp://x:1'})['worker_id']

    with MaterializeController(dataset.url,
                               str(tmp_path / 'plane')) as controller:
        dispatcher.attach_materializer(controller)
        assert controller.offer_drain_candidate(
            w0, deadline_s=Dispatcher.DRAIN_WARM_DEADLINE_S)
        now = time.monotonic()
        dispatcher._deferred_drains[w0] = \
            now + Dispatcher.DRAIN_WARM_DEADLINE_S
        dispatcher.materialize_handoffs += 1

        # While the pass runs the drain is deferred, not executed.
        if not controller.drain_ready(w0):
            dispatcher._tick_deferred_drains(time.monotonic())
            assert not dispatcher._workers[w0].get('draining')

        deadline = time.monotonic() + 30.0
        while not controller.drain_ready(w0):
            assert time.monotonic() < deadline, 'warming pass never finished'
            time.sleep(0.05)
        dispatcher._tick_deferred_drains(time.monotonic())
        assert w0 not in dispatcher._deferred_drains
        assert dispatcher._workers[w0]['draining']
        # The offered capacity actually warmed pieces before draining.
        assert controller.summary()['done'] == \
            controller.summary()['total_pieces']
    assert dispatcher.materialize_handoffs == 1
    snapshot = dispatcher._fleet_snapshot()
    assert snapshot['counters']['materialize_handoffs'] == 1


def test_deferred_drain_deadline_wins_over_a_stuck_pass(dataset):
    """Warming can delay a drain, never veto it: a pass that outlives
    the deadline drains anyway."""
    from petastorm_tpu.service import Dispatcher, ServiceConfig

    class _StuckMaterializer(object):
        def drain_ready(self, worker_id):
            return False

    config = ServiceConfig(dataset.url, num_consumers=1,
                           rowgroups_per_split=2, lease_ttl_s=2.0)
    dispatcher = Dispatcher(config, num_pieces=2)
    w0 = dispatcher._op_register_worker({'data_addr': 'tcp://x:1'})['worker_id']
    dispatcher.attach_materializer(_StuckMaterializer())
    dispatcher._deferred_drains[w0] = time.monotonic() - 1.0  # deadline past
    dispatcher._tick_deferred_drains(time.monotonic())
    assert dispatcher._workers[w0]['draining']
    assert w0 not in dispatcher._deferred_drains


# -- chaos: SIGKILL mid-publish (satellite 3) --------------------------------

def test_materialize_kill_scenario_registered():
    from petastorm_tpu.test_util import chaos
    scenario = chaos.SCENARIOS['materialize_kill']
    assert scenario['runner'] == 'materialize'
    assert scenario['throttle_s'] > 0       # the kill window
    assert 'materialize_kill' not in chaos.SMOKE_SCENARIOS


def test_materialize_kill_scenario_end_to_end(tmp_path):
    """SIGKILL the controller mid-publish: zero torn entries, the ledger
    resumes attempt-intact, and the consumer's delivery digest through
    the half-then-fully warmed plane matches ground truth."""
    from petastorm_tpu.test_util import chaos
    url, rows = chaos.make_chaos_dataset(str(tmp_path / 'ds'), seed=13)
    report = chaos.run_scenario('materialize_kill', url, rows,
                                str(tmp_path), seed=13)
    assert report['ok'], report
    checks = report['checks']
    for name in ('zero_torn_entries', 'ledger_progress', 'resume',
                 'digest', 'served_from_plane', 'zero_residue'):
        assert checks[name].startswith('ok'), (name, checks)


# -- doctor probe (satellite 4) ----------------------------------------------

def test_doctor_materialize_probe_reports_skip_stages():
    from petastorm_tpu.tools.doctor import _check_materialize
    out = _check_materialize()
    assert out['roundtrip_ok'], out
    assert out['skip_decode'] and out['skip_collate'] and out['skip_narrow']
    assert out['warmed_pieces'] == 2
    assert out['wire_published'] == 2


def test_doctor_materialize_probe_honors_kill_switch(monkeypatch):
    from petastorm_tpu.tools.doctor import _check_materialize
    monkeypatch.setenv('PETASTORM_TPU_NO_MATERIALIZE', '1')
    out = _check_materialize()
    assert out == {'kill_switch': True, 'note': out['note']}
