"""On-disk compatibility with reference-written datasets.

The reference pickles its ``Unischema`` under the module paths
``petastorm.unischema`` / ``petastorm.codecs``, with ``ScalarCodec`` holding
**pyspark sql DataType instances** (``petastorm/codecs.py ::
ScalarCodec.spark_dtype``).  Our footer reader remaps the module paths and
satisfies the pyspark lookups with stub classes, so real petastorm datasets
open unmodified on TPU-VM images that ship no pyspark (SURVEY.md §7 risk:
footer-metadata compatibility).

Two layers of evidence:

* a **frozen byte-exact fixture** (``tests/data/reference_unischema_footer
  .b64``, generated once by ``tests/data/gen_reference_footer_fixture.py``
  from independently synthesized reference-layout classes — NOT from
  petastorm_tpu classes) unpickles into a working schema with pyspark absent;
* a protocol-0 module-rename check (the original round-1 test) still guards
  the rename table itself.
"""

import base64
import os
import pickle
from decimal import Decimal

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from petastorm_tpu import make_reader
from petastorm_tpu.codecs import (CompressedImageCodec, CompressedNdarrayCodec,
                                  NdarrayCodec, ScalarCodec)
from petastorm_tpu.etl import dataset_metadata as dm
from petastorm_tpu.unischema import Unischema
from tests.test_common import assert_rows_equal, create_test_dataset

FIXTURE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       'data', 'reference_unischema_footer.b64')


def _fixture_bytes():
    with open(FIXTURE) as f:
        return base64.b64decode(f.read())


@pytest.fixture()
def no_pyspark(monkeypatch):
    """Simulate a pyspark-free host (sys.modules[...] = None makes import
    raise ImportError) so the stub-layer tests prove what they claim even in
    a dev environment that has pyspark installed."""
    import sys
    for mod in ('pyspark', 'pyspark.sql', 'pyspark.sql.types'):
        monkeypatch.setitem(sys.modules, mod, None)


def test_environment_note():
    """TPU-VM images ship no pyspark (SURVEY.md §7); in dev environments
    that DO have it, the no_pyspark fixture simulates its absence."""
    try:
        import pyspark  # noqa: F401
        pytest.skip('pyspark installed here; stub tests use the no_pyspark fixture')
    except ImportError:
        pass  # the deployment reality these tests target


def test_frozen_reference_footer_unpickles_without_pyspark(no_pyspark):
    blob = _fixture_bytes()
    assert b'petastorm_tpu' not in blob  # genuinely foreign bytes
    assert b'pyspark' in blob

    schema = dm._loads_schema(blob)
    assert isinstance(schema, Unischema)
    assert schema.name == 'RefSchema'
    assert sorted(schema.fields) == ['id', 'image', 'label', 'matrix',
                                     'price', 'sparse']

    # ScalarCodec spark types recovered through the stub layer:
    assert isinstance(schema.fields['id'].codec, ScalarCodec)
    assert schema.fields['id'].codec.arrow_dtype() == pa.int32()
    assert schema.fields['label'].codec.arrow_dtype() == pa.string()
    assert schema.fields['price'].codec.arrow_dtype() == pa.decimal128(10, 2)
    # Binary codecs map onto ours with their exact state:
    assert isinstance(schema.fields['matrix'].codec, NdarrayCodec)
    assert isinstance(schema.fields['sparse'].codec, CompressedNdarrayCodec)
    image_codec = schema.fields['image'].codec
    assert isinstance(image_codec, CompressedImageCodec)
    assert image_codec.image_codec == 'png' and image_codec.quality == 80
    # Field tuples carry the reference layout verbatim:
    assert schema.fields['matrix'].shape == (4, 3)
    assert schema.fields['label'].nullable is True


def test_end_to_end_read_over_reference_footer(tmp_path, no_pyspark):
    """Write cells in the (shared) on-disk format, then splice the frozen
    reference footer in — the reader must decode rows with no petastorm_tpu
    schema anywhere on disk."""
    from petastorm_tpu.etl.dataset_metadata import DatasetWriter

    schema = dm._loads_schema(_fixture_bytes())
    rng = np.random.default_rng(7)
    rows = []
    for i in range(12):
        rows.append({
            'id': np.int32(i),
            'label': 'item-%d' % i if i % 3 else None,
            'price': Decimal('%d.%02d' % (i, i)),
            'matrix': rng.standard_normal((4, 3)).astype(np.float32),
            'sparse': rng.standard_normal(8).astype(np.float64),
            'image': rng.integers(0, 255, (6, 5, 3), dtype=np.uint8),
        })
    url = 'file://' + str(tmp_path / 'refds')
    with DatasetWriter(url, schema, rows_per_rowgroup=4) as w:
        for row in rows:
            w.write(row)

    # Replace the footer blob with the EXACT frozen reference bytes.
    meta_path = str(tmp_path / 'refds') + '/_common_metadata'
    arrow_schema = pq.read_schema(meta_path)
    metadata = dict(arrow_schema.metadata)
    metadata[dm.UNISCHEMA_KEY] = _fixture_bytes()
    pq.write_metadata(arrow_schema.with_metadata(metadata), meta_path)

    with make_reader(url, reader_pool_type='dummy',
                     shuffle_row_groups=False) as reader:
        got = sorted([r._asdict() for r in reader], key=lambda r: int(r['id']))
    assert len(got) == 12
    for want, have in zip(rows, got):
        assert int(have['id']) == int(want['id'])
        assert have['label'] == want['label']
        assert Decimal(have['price']) == want['price']
        np.testing.assert_array_equal(have['matrix'], want['matrix'])
        np.testing.assert_array_equal(have['sparse'], want['sparse'])
        np.testing.assert_array_equal(have['image'], want['image'])


def test_fixture_matches_generator():
    """The frozen bytes stay reproducible from the committed generator (run
    in a subprocess so its sys.modules fakery cannot leak into this one)."""
    import subprocess
    import sys
    gen = os.path.join(os.path.dirname(FIXTURE), 'gen_reference_footer_fixture.py')
    code = (
        'import importlib.util, base64, sys\n'
        'spec = importlib.util.spec_from_file_location("gen", %r)\n'
        'mod = importlib.util.module_from_spec(spec)\n'
        'spec.loader.exec_module(mod)\n'
        'sys.stdout.write(base64.b64encode(mod.build_fixture_bytes()).decode())\n'
    ) % gen
    out = subprocess.run([sys.executable, '-c', code], capture_output=True,
                         text=True, check=True)
    assert out.stdout.strip() == open(FIXTURE).read().strip()


# -- round-1 rename-table guard (protocol 0) ---------------------------------

def _doctor_footer_to_reference_modules(path):
    """Rewrite _common_metadata so the pickled schema claims petastorm.*"""
    meta_path = path + '/' + '_common_metadata'
    arrow_schema = pq.read_schema(meta_path)
    blob = arrow_schema.metadata[dm.UNISCHEMA_KEY]
    schema_obj = pickle.loads(blob)
    doctored = pickle.dumps(schema_obj, protocol=0).replace(
        b'petastorm_tpu.', b'petastorm.')
    assert b'petastorm.unischema' in doctored
    assert b'petastorm_tpu' not in doctored
    metadata = dict(arrow_schema.metadata)
    metadata[dm.UNISCHEMA_KEY] = doctored
    pq.write_metadata(arrow_schema.with_metadata(metadata), meta_path)


def test_reads_reference_pickled_unischema(tmp_path):
    ds = create_test_dataset('file://' + str(tmp_path / 'refds'), num_rows=20,
                             rows_per_rowgroup=5)
    _doctor_footer_to_reference_modules(ds.path)

    # Fresh read resolves petastorm.unischema.Unischema -> ours.
    schema = dm.get_schema_from_dataset_url(ds.url)
    assert sorted(schema.fields) == sorted(
        ['id', 'id2', 'image_png', 'matrix', 'decimal_like', 'embedding',
         'sensor_name', 'nullable_scalar'])

    with make_reader(ds.url, reader_pool_type='dummy',
                     shuffle_row_groups=False) as reader:
        assert_rows_equal(list(reader), ds.data)


def test_unknown_modules_still_fail_loudly(tmp_path):
    """The shim remaps only known petastorm/pyspark modules — arbitrary
    pickles still raise (no silent wrong-class resolution)."""
    blob = pickle.dumps(np.float64(1.0), protocol=0).replace(b'numpy', b'nonexistent_mod')
    with pytest.raises(Exception):
        dm._loads_schema(blob)


def test_stub_layer_scoped_to_pyspark_sql_types():
    """Only pyspark.sql.types lookups get stubbed; other pyspark modules
    (if referenced) still raise rather than resolving to a fake."""
    blob = pickle.dumps(np.float64(1.0), protocol=0).replace(
        b'numpy', b'pyspark.rdd')
    with pytest.raises(Exception):
        dm._loads_schema(blob)
