"""On-disk compatibility with reference-written datasets.

The reference pickles its ``Unischema`` under the module paths
``petastorm.unischema`` / ``petastorm.codecs``; our footer reader remaps
them through ``_CompatUnpickler`` so real petastorm datasets open
unmodified (SURVEY.md §7 risk: footer-metadata compatibility).

A reference footer is fabricated here by re-pickling our schema at
protocol 0 (module names are stored as length-free text) and rewriting
``petastorm_tpu.`` → ``petastorm.`` — byte-exact to what the reference's
``materialize_dataset`` would emit for an equivalent schema.
"""

import pickle

import numpy as np
import pyarrow.parquet as pq

from petastorm_tpu import make_reader
from petastorm_tpu.etl import dataset_metadata as dm
from tests.test_common import assert_rows_equal, create_test_dataset


def _doctor_footer_to_reference_modules(path):
    """Rewrite _common_metadata so the pickled schema claims petastorm.*"""
    meta_path = path + '/' + '_common_metadata'
    arrow_schema = pq.read_schema(meta_path)
    blob = arrow_schema.metadata[dm.UNISCHEMA_KEY]
    schema_obj = pickle.loads(blob)
    doctored = pickle.dumps(schema_obj, protocol=0).replace(
        b'petastorm_tpu.', b'petastorm.')
    assert b'petastorm.unischema' in doctored
    assert b'petastorm_tpu' not in doctored
    metadata = dict(arrow_schema.metadata)
    metadata[dm.UNISCHEMA_KEY] = doctored
    pq.write_metadata(arrow_schema.with_metadata(metadata), meta_path)


def test_reads_reference_pickled_unischema(tmp_path):
    ds = create_test_dataset('file://' + str(tmp_path / 'refds'), num_rows=20,
                             rows_per_rowgroup=5)
    _doctor_footer_to_reference_modules(ds.path)

    # Fresh read resolves petastorm.unischema.Unischema -> ours.
    schema = dm.get_schema_from_dataset_url(ds.url)
    assert sorted(schema.fields) == sorted(
        ['id', 'id2', 'image_png', 'matrix', 'decimal_like', 'embedding',
         'sensor_name', 'nullable_scalar'])

    with make_reader(ds.url, reader_pool_type='dummy',
                     shuffle_row_groups=False) as reader:
        assert_rows_equal(list(reader), ds.data)


def test_unknown_modules_still_fail_loudly(tmp_path):
    """The shim remaps only known petastorm modules — arbitrary pickles
    still raise (no silent wrong-class resolution)."""
    import pytest
    blob = pickle.dumps(np.float64(1.0), protocol=0).replace(b'numpy', b'nonexistent_mod')
    with pytest.raises(Exception):
        dm._loads_schema(blob)
