"""make_batch_reader over plain (non-petastorm) Parquet.

Modeled on the reference's ``petastorm/tests/test_parquet_reader.py``.
"""

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from petastorm_tpu import make_batch_reader
from petastorm_tpu.predicates import in_lambda
from petastorm_tpu.transform import TransformSpec


@pytest.fixture(scope='module')
def plain_parquet(tmp_path_factory):
    path = tmp_path_factory.mktemp('plain')
    df = pd.DataFrame({
        'idx': np.arange(100, dtype=np.int64),
        'value': np.arange(100, dtype=np.float64) * 0.5,
        'name': ['row_%d' % i for i in range(100)],
        'vec': [np.arange(4, dtype=np.float32) + i for i in range(100)],
    })
    table = pa.Table.from_pandas(df, preserve_index=False)
    pq.write_table(table, str(path / 'data.parquet'), row_group_size=20)
    return 'file://' + str(path), df


def _collect(reader):
    batches = []
    with reader:
        for batch in reader:
            batches.append(batch)
    return batches


def test_batches_cover_all_rows(plain_parquet):
    url, df = plain_parquet
    batches = _collect(make_batch_reader(url, reader_pool_type='dummy'))
    assert len(batches) == 5  # 100 rows / 20 per group
    ids = np.concatenate([b.idx for b in batches])
    assert sorted(ids.tolist()) == list(range(100))
    values = np.concatenate([b.value for b in batches])
    assert set(values.tolist()) == set((np.arange(100) * 0.5).tolist())


def test_list_column_stacks_rectangular(plain_parquet):
    url, _ = plain_parquet
    batches = _collect(make_batch_reader(url, reader_pool_type='dummy',
                                         shuffle_row_groups=False))
    vec = batches[0].vec
    assert vec.shape == (20, 4)
    np.testing.assert_array_equal(vec[3], np.arange(4) + 3)


def test_column_projection(plain_parquet):
    url, _ = plain_parquet
    batches = _collect(make_batch_reader(url, schema_fields=['idx', 'value'],
                                         reader_pool_type='dummy'))
    assert set(batches[0]._fields) == {'idx', 'value'}


def test_predicate_on_batch_path(plain_parquet):
    url, _ = plain_parquet
    batches = _collect(make_batch_reader(
        url, predicate=in_lambda(['idx'], lambda v: v['idx'] < 30),
        reader_pool_type='dummy'))
    ids = np.concatenate([b.idx for b in batches])
    assert sorted(ids.tolist()) == list(range(30))


def test_transform_spec_pandas(plain_parquet):
    url, _ = plain_parquet

    def double(df):
        df = df.copy()
        df['value'] = df['value'] * 2
        return df

    batches = _collect(make_batch_reader(
        url, schema_fields=['idx', 'value'],
        transform_spec=TransformSpec(double), reader_pool_type='dummy',
        shuffle_row_groups=False))
    np.testing.assert_allclose(batches[0].value, np.arange(20) * 1.0)


def test_sharding_batch_path(plain_parquet):
    url, _ = plain_parquet
    seen = set()
    for shard in range(2):
        batches = _collect(make_batch_reader(url, cur_shard=shard, shard_count=2,
                                             reader_pool_type='dummy'))
        ids = {int(i) for b in batches for i in b.idx}
        assert seen.isdisjoint(ids)
        seen |= ids
    assert seen == set(range(100))


def test_thread_pool_batch(plain_parquet):
    url, _ = plain_parquet
    batches = _collect(make_batch_reader(url, reader_pool_type='thread', workers_count=3))
    ids = np.concatenate([b.idx for b in batches])
    assert sorted(ids.tolist()) == list(range(100))


def test_partitioned_directory(tmp_path):
    """Hive-partitioned dataset: partition key materialized from dir names."""
    for part in (0, 1):
        sub = tmp_path / ('part=%d' % part)
        sub.mkdir()
        df = pd.DataFrame({'idx': np.arange(5, dtype=np.int64) + 5 * part})
        pq.write_table(pa.Table.from_pandas(df, preserve_index=False),
                       str(sub / 'f.parquet'))
    batches = _collect(make_batch_reader('file://' + str(tmp_path),
                                         reader_pool_type='dummy'))
    ids = sorted(int(i) for b in batches for i in b.idx)
    assert ids == list(range(10))
