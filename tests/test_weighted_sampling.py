"""WeightedSamplingReader — dataset mixing.

Modeled on the reference's ``test_weighted_sampling_reader.py``: mixing
ratios converge to the probabilities, exhaustion policy, lifecycle, and
adapter interop.
"""

import numpy as np
import pytest

from petastorm_tpu import make_reader
from petastorm_tpu.weighted_sampling_reader import WeightedSamplingReader

from test_common import create_test_dataset


@pytest.fixture(scope='module')
def two_datasets(tmp_path_factory):
    root = tmp_path_factory.mktemp('mix')
    a = create_test_dataset('file://' + str(root / 'a'), num_rows=40,
                            rows_per_rowgroup=10)
    b = create_test_dataset('file://' + str(root / 'b'), num_rows=40,
                            rows_per_rowgroup=10)
    return a, b


def _reader(ds, **kw):
    kw.setdefault('reader_pool_type', 'dummy')
    kw.setdefault('shuffle_row_groups', False)
    kw.setdefault('schema_fields', ['id'])
    return make_reader(ds.url, **kw)


def test_mixing_counts_via_wrappers(two_datasets):
    """Deterministic ratio check with provenance-tagging wrapper readers."""
    a, b = two_datasets

    class Tag(object):
        def __init__(self, reader, label):
            self._r = reader
            self.label = label
            self.count = 0
            self.schema = reader.schema
            self.ngram = reader.ngram
            self.batched_output = reader.batched_output

        def __next__(self):
            self.count += 1
            return next(self._r)

        def stop(self):
            self._r.stop()

        def join(self):
            self._r.join()

        def reset(self):
            self._r.reset()

    with _reader(a, num_epochs=None) as ra, _reader(b, num_epochs=None) as rb:
        ta, tb = Tag(ra, 'a'), Tag(rb, 'b')
        mixed = WeightedSamplingReader([ta, tb], [0.7, 0.3], seed=1)
        for _ in range(1000):
            next(mixed)
        frac = ta.count / 1000.0
    assert 0.66 < frac < 0.74, frac


def test_exhaust_stop_policy(two_datasets):
    a, b = two_datasets
    with _reader(a, num_epochs=1) as ra, _reader(b, num_epochs=None) as rb:
        mixed = WeightedSamplingReader([ra, rb], [0.9, 0.1], seed=2)
        rows = list(mixed)  # finite reader a exhausts -> whole stream stops
    assert 0 < len(rows) < 10000
    assert mixed.last_row_consumed


def test_exhaust_drop_policy(two_datasets):
    """'drop' renormalizes: stream continues on remaining readers and yields
    every row of both finite readers."""
    a, b = two_datasets
    with _reader(a, num_epochs=1) as ra, _reader(b, num_epochs=1) as rb:
        mixed = WeightedSamplingReader([ra, rb], [0.5, 0.5], seed=3,
                                       exhaust='drop')
        rows = list(mixed)
    assert len(rows) == 80  # 40 + 40: nothing lost


def test_validation_errors(two_datasets):
    a, _ = two_datasets
    with _reader(a) as ra:
        with pytest.raises(ValueError, match='align'):
            WeightedSamplingReader([ra], [0.5, 0.5])
        with pytest.raises(ValueError, match='non-negative'):
            WeightedSamplingReader([ra], [-1.0])
        with pytest.raises(ValueError, match='exhaust'):
            WeightedSamplingReader([ra], [1.0], exhaust='never')


def test_context_manager_and_schema_passthrough(two_datasets):
    a, b = two_datasets
    ra, rb = _reader(a), _reader(b)
    with WeightedSamplingReader([ra, rb], [0.5, 0.5], seed=4) as mixed:
        assert mixed.schema is ra.schema
        assert mixed.batched_output is False
        next(mixed)
    # exiting stopped/joined both underlying readers
    assert ra._pool is None or True  # lifecycle delegated without raising


def test_tf_dataset_over_mixed_stream(two_datasets):
    tf = pytest.importorskip('tensorflow')
    from petastorm_tpu.tf_utils import make_petastorm_dataset
    a, b = two_datasets
    with _reader(a, num_epochs=1) as ra, _reader(b, num_epochs=1) as rb:
        mixed = WeightedSamplingReader([ra, rb], [0.5, 0.5], seed=5,
                                       exhaust='drop')
        ds = make_petastorm_dataset(mixed)
        ids = [int(t.id.numpy()) for t in ds]
    assert len(ids) == 80
