"""Cluster-wide cache tier (ISSUE 10): cache-affinity lease routing,
remote HIT serving, and peer fill.

The correctness spine is unchanged from the service's core promise —
exactly-once, bit-identical delivery — with three new ways to get there
cheaper.  The tests pin, in order: the digest identity (what a cluster
worker computes WITHOUT a reader must equal what a real reader's plane
publishes — the anti-drift contract over the key formats), the lease
routing rules (affinity prefers warm workers, bounded deferral, and an
expired lease is NEVER delayed by affinity), the data plane (peer fetch
round trip, peer fill publishing, SIGKILLed-peer degrade with zero
residue), and the fingerprint-invariance satellite (scheduling /
transfer / autotune knobs must not de-warm the fleet's cache).
"""

import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from petastorm_tpu import make_batch_reader, make_reader
from petastorm_tpu.service import (Dispatcher, ServiceConfig,
                                   ServiceDataLoader, Worker)
from petastorm_tpu.service import cluster
from petastorm_tpu.service import dispatcher as dispatcher_mod

from test_common import create_test_dataset, shm_residue

ROWS = 60
ROWS_PER_GROUP = 4          # -> 15 row groups
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope='module')
def dataset(tmp_path_factory):
    path = tmp_path_factory.mktemp('clusterds')
    return create_test_dataset('file://' + str(path), num_rows=ROWS,
                               rows_per_rowgroup=ROWS_PER_GROUP)


def _job(dataset, plane_dir, **overrides):
    config = _config(dataset, plane_dir, **overrides)
    return dict(config.job_info(15), cache_plane_dir=plane_dir)


def _config(dataset, plane_dir, **overrides):
    overrides.setdefault('rowgroups_per_split', 2)
    overrides.setdefault('lease_ttl_s', 5.0)
    overrides.setdefault('reader_kwargs', {'workers_count': 2})
    return ServiceConfig(dataset.url, num_consumers=1,
                         cache_plane=True, cache_plane_dir=plane_dir,
                         **overrides)


def _consume(dispatcher_addr, **loader_kwargs):
    loader = ServiceDataLoader(dispatcher_addr, batch_size=8, consumer=0,
                               drop_last=False, **loader_kwargs)
    ids = []
    with loader:
        for batch in loader.iter_host_batches():
            ids.extend(np.asarray(batch['id']).tolist())
    return sorted(ids)


# -- digest identity: the anti-drift contract ---------------------------------

def test_identity_digests_match_real_reader(tmp_path, dataset):
    """What ClusterCacheIdentity computes from footer metadata alone must
    name exactly the entries a real per-split reader publishes — and
    serving those entries must be bit-identical to the reader's output.
    If a future change drifts the reader's key format away from the
    shared helpers, this test goes red."""
    plane_dir = str(tmp_path / 'plane')
    job = _job(dataset, plane_dir)
    identity = cluster.ClusterCacheIdentity.build(job)
    assert identity is not None
    assert identity.num_pieces == 15
    indices = [0, 1, 2]
    assert len(identity.missing_digests(indices)) == 3  # cold plane
    assert identity.serve_chunks(indices) is None

    # workers_count=1: the deterministic split-reader config (a
    # multi-worker FIFO pool delivers in completion order — the service
    # documents that full determinism needs a deterministic reader).
    # Remote-HIT serving always streams in piece order, i.e. exactly
    # this deterministic order.
    with make_reader(dataset.url, piece_indices=indices, num_epochs=1,
                     shuffle_row_groups=False, columnar_decode=True,
                     cache_type='plane', cache_location=plane_dir,
                     workers_count=1) as reader:
        expected = [item._asdict() for item in reader]

    # The reader's plane publishes under EXACTLY the digests the
    # identity predicted...
    assert identity.missing_digests(indices) == []
    # ...and serving them reproduces the reader's chunks bit-for-bit.
    served = identity.serve_chunks(indices)
    assert len(served) == len(expected)
    for got, want in zip(served, expected):
        assert sorted(got) == sorted(want)
        for key in want:
            np.testing.assert_array_equal(np.asarray(got[key]),
                                          np.asarray(want[key]))


def test_identity_batch_reader_path(tmp_path):
    """Same contract for plain-Parquet jobs (the arrow/batch worker)."""
    import pyarrow as pa
    import pyarrow.parquet as pq
    path = str(tmp_path / 'plain.parquet')
    table = pa.table({'x': np.arange(24, dtype=np.int64),
                      'y': np.arange(24, dtype=np.float64) * 0.5})
    pq.write_table(table, path, row_group_size=6)   # 4 row groups
    url = 'file://' + path
    plane_dir = str(tmp_path / 'plane')
    config = ServiceConfig(url, num_consumers=1, rowgroups_per_split=2,
                           reader_factory='batch_reader',
                           cache_plane=True, cache_plane_dir=plane_dir)
    job = config.job_info(4)
    identity = cluster.ClusterCacheIdentity.build(job)
    assert identity is not None and identity.num_pieces == 4
    with make_batch_reader(url, piece_indices=[1, 2], num_epochs=1,
                           shuffle_row_groups=False, cache_type='plane',
                           cache_location=plane_dir,
                           workers_count=1) as reader:
        expected = [item._asdict() for item in reader]
    assert identity.missing_digests([1, 2]) == []
    served = identity.serve_chunks([1, 2])
    assert len(served) == len(expected) == 2
    for got, want in zip(served, expected):
        for key in want:
            np.testing.assert_array_equal(np.asarray(got[key]),
                                          np.asarray(want[key]))


def test_identity_unsupported_kwargs_disable_cluster(tmp_path, dataset):
    plane_dir = str(tmp_path / 'plane')
    job = _job(dataset, plane_dir)
    job['reader_kwargs'] = {'rowgroup_selector': object()}
    assert cluster.ClusterCacheIdentity.build(job) is None
    job['reader_kwargs'] = {'cache_type': 'local-disk'}
    assert cluster.ClusterCacheIdentity.build(job) is None


def test_enabled_kill_switch(monkeypatch, tmp_path, dataset):
    job = _job(dataset, str(tmp_path / 'p'))
    assert cluster.enabled(job)
    monkeypatch.setenv(cluster.KILL_ENV, '1')
    assert not cluster.enabled(job)
    monkeypatch.delenv(cluster.KILL_ENV)
    job['cluster_cache'] = False
    assert not cluster.enabled(job)


# -- lease routing rules (dispatcher-level, deterministic) --------------------

def _fake_fleet(dataset, plane_dir):
    """A dispatcher plus two registered workers, directory primed so w0
    holds EVERY piece digest and w1 holds nothing."""
    config = _config(dataset, plane_dir)
    dispatcher = Dispatcher(config, num_pieces=15)
    w0 = dispatcher._op_register_worker(
        {'data_addr': 'tcp://127.0.0.1:4441'})['worker_id']
    w1 = dispatcher._op_register_worker(
        {'data_addr': 'tcp://127.0.0.1:4442'})['worker_id']
    digests = ['d%012d' % i for i in range(15)]
    dispatcher._op_heartbeat({'worker_id': w0, 'piece_digests': digests,
                              'cache_digests': digests})
    dispatcher._op_heartbeat({'worker_id': w1, 'cache_digests': []})
    return dispatcher, w0, w1


def test_affinity_prefers_holder_and_defers_bounded(tmp_path, dataset):
    dispatcher, w0, w1 = _fake_fleet(dataset, str(tmp_path / 'p'))
    # A cold worker asking first is kept waiting (the holder's bounded
    # preference window)...
    reply = dispatcher._op_lease({'worker_id': w1})
    assert reply.get('wait') and dispatcher.affinity_deferrals >= 1
    # ...the warm worker gets its split, counted as affinity-routed,
    # with no holders hint (it holds everything itself).
    reply = dispatcher._op_lease({'worker_id': w0})
    assert reply['split']['split_id'] == 0
    assert dispatcher.affinity_routed == 1
    assert 'holders' not in reply
    # Past the preference window the cold worker gets a split anyway
    # (affinity must not starve a worker), WITH peer-fill hints at w0.
    for split in dispatcher._splits:
        if split.affinity_defer_until is not None:
            split.affinity_defer_until = time.monotonic() - 0.01
    reply = dispatcher._op_lease({'worker_id': w1})
    assert reply.get('split') is not None
    assert reply['holders']
    assert all(addrs == ['tcp://127.0.0.1:4441']
               for addrs in reply['holders'].values())


def test_expired_lease_reassigns_without_affinity_delay(tmp_path, dataset):
    """THE acceptance pin: a split whose lease expired (attempt > 0) goes
    to the first asking worker immediately — even a cold one, even while
    a live warm holder exists.  Affinity may reorder fresh work; it must
    never sit on failure recovery."""
    dispatcher, w0, w1 = _fake_fleet(dataset, str(tmp_path / 'p'))
    reply = dispatcher._op_lease({'worker_id': w0})
    split_id = reply['split']['split_id']
    # w0 dies: its lease expires and the split requeues (attempt=1).
    split = dispatcher._splits[split_id]
    split.lease_expires = time.monotonic() - 1.0
    dispatcher._expire_leases()
    assert split.state == 'pending' and split.attempt == 1
    # The cold worker's very next ask gets it — no preference window.
    reply = dispatcher._op_lease({'worker_id': w1})
    assert reply['split']['split_id'] == split_id
    # (the grant still ships holder hints so w1 can peer-fill)
    assert reply.get('holders')


def test_lease_without_directory_is_plain_fifo(tmp_path, dataset):
    """No piece map / no advertisements (or the kill switch): the lease
    path is the pre-cluster FIFO, bit-identical."""
    config = _config(dataset, str(tmp_path / 'p'))
    dispatcher = Dispatcher(config, num_pieces=15)
    w0 = dispatcher._op_register_worker(
        {'data_addr': 'tcp://127.0.0.1:4443'})['worker_id']
    granted = [dispatcher._op_lease({'worker_id': w0})['split']['split_id']
               for _ in range(3)]
    assert granted == [0, 1, 2]
    assert dispatcher.affinity_routed == 0
    assert dispatcher.affinity_deferrals == 0


# -- peer fetch data plane ----------------------------------------------------

def _router_peer(plane, stop_event, addr_box):
    """A minimal peer: ROUTER socket answering fetch requests with
    cluster.fetch_reply — the same function the real worker event loop
    calls."""
    import pickle

    import zmq
    context = zmq.Context()
    sock = context.socket(zmq.ROUTER)
    sock.setsockopt(zmq.LINGER, 0)
    port = sock.bind_to_random_port('tcp://127.0.0.1')
    addr_box.append('tcp://127.0.0.1:%d' % port)
    try:
        while not stop_event.is_set():
            if not sock.poll(50):
                continue
            identity, raw = sock.recv_multipart()
            sock.send_multipart(cluster.fetch_reply(
                identity, pickle.loads(raw), plane))
    finally:
        sock.close(0)
        context.term()


def test_peer_fetch_round_trip_and_missing(tmp_path):
    """PeerFetcher against a real socket served by fetch_reply: present
    digests come back byte-identical, absent ones degrade to None."""
    import zmq

    from petastorm_tpu.cache_plane import CachePlane
    from petastorm_tpu.cache_plane.plane import encode_entry
    plane = CachePlane(str(tmp_path / 'p'), ram_capacity_bytes=0)
    blob = bytes(encode_entry({'x': np.arange(32)}))
    digest = plane.digest('probe-key')
    assert plane.publish_blob(digest, blob)
    assert plane.entry_blob(digest) == blob

    stop, addrs = threading.Event(), []
    peer = threading.Thread(target=_router_peer, args=(plane, stop, addrs),
                            daemon=True)
    peer.start()
    for _ in range(100):
        if addrs:
            break
        time.sleep(0.01)
    context = zmq.Context()
    fetcher = cluster.PeerFetcher(context, timeout_s=5.0)
    try:
        assert fetcher.fetch(addrs[0], digest) == blob
        assert fetcher.fetch(addrs[0], 'f' * 32) is None   # absent
    finally:
        fetcher.close()
        stop.set()
        peer.join(5)
        context.term()


def test_peer_fetch_times_out_on_dead_peer(tmp_path):
    import zmq
    context = zmq.Context()
    fetcher = cluster.PeerFetcher(context, timeout_s=0.3)
    try:
        t0 = time.monotonic()
        assert fetcher.fetch('tcp://127.0.0.1:1', 'a' * 32) is None
        assert time.monotonic() - t0 < 3.0   # bounded, not wedged
    finally:
        fetcher.close()
        context.term()


# -- end to end: the three mechanisms over the real wire ----------------------

def _run_fleet(dataset, shared_plane_dir, worker_plane_dirs,
               wait_digests=0, **overrides):
    config = _config(dataset, shared_plane_dir, **overrides)
    with Dispatcher(config) as dispatcher:
        workers = [Worker(dispatcher.addr, cache_plane_dir=p).start()
                   for p in worker_plane_dirs]
        try:
            if wait_digests:
                # Let the warm worker's advertisement land before any
                # lease is granted — identity builds in the background
                # and rides heartbeats, so without this the first few
                # splits race it (fine in production, flaky in a test
                # that asserts exact counter totals).
                deadline = time.monotonic() + 30
                while time.monotonic() < deadline:
                    rollup = _stats(dispatcher.addr)['cluster_cache']
                    if rollup['piece_map'] \
                            and rollup['directory_digests'] >= wait_digests:
                        break
                    time.sleep(0.2)
                else:
                    raise AssertionError('directory never primed: %r'
                                         % (rollup,))
            ids = _consume(dispatcher.addr)
            diags = [w.diagnostics for w in workers]
        finally:
            for w in workers:
                w.stop()
            for w in workers:
                w.join()
    return ids, diags


def test_warm_worker_serves_remote_hits_cold_joiner_peer_fills(
        tmp_path, dataset, monkeypatch):
    """The tentpole end to end: a fleet where one worker's plane already
    holds the dataset serves it without decoding; with the preference
    window zeroed (so lease races are deterministic-ish) the cold
    joiner's splits peer-fill from the warm plane and publish locally."""
    plane_a = str(tmp_path / 'planeA')
    plane_b = str(tmp_path / 'planeB')
    ids_prep, diag_prep = _run_fleet(dataset, plane_a, [plane_a])
    assert ids_prep == list(range(ROWS))
    assert diag_prep[0]['cache_misses'] == 15   # cold decode, once

    monkeypatch.setattr(dispatcher_mod, '_AFFINITY_DEFER_S', 0.0)
    ids, diags = _run_fleet(dataset, plane_a, [plane_b, plane_a],
                            wait_digests=15)
    assert ids == list(range(ROWS))
    total = {key: sum(d[key] for d in diags)
             for key in ('cache_remote_hits', 'cache_peer_fills',
                         'cache_peer_degraded', 'cache_misses')}
    # Nothing decoded twice anywhere: every piece either served straight
    # from a plane or crossed as a peer fill.
    assert total['cache_misses'] == 0
    assert total['cache_remote_hits'] == 15
    assert total['cache_peer_degraded'] == 0
    # The cold joiner really pulled entries across (unless it lost every
    # lease race, which the zeroed window makes effectively impossible
    # on a 8-split epoch — but the assertion stays on the B-side plane).
    if diags[0]['splits_decoded']:
        assert diags[0]['cache_peer_fills'] > 0
        assert any(name.endswith('.cpe') for name in os.listdir(plane_b))


def test_peer_sigkilled_mid_fetch_degrades_to_direct_decode(
        tmp_path, dataset, monkeypatch):
    """Satellite pin: holder hints pointing at a dead peer cost one
    bounded timeout each, count cache_peer_degraded, and the split
    decodes directly — full delivery, zero shm/tmp residue."""
    plane_a = str(tmp_path / 'planeA')
    plane_b = str(tmp_path / 'planeB')
    ids_prep, _ = _run_fleet(dataset, plane_a, [plane_a])
    assert ids_prep == list(range(ROWS))

    config = _config(dataset, plane_a)
    monkeypatch.setattr(cluster, 'FETCH_TIMEOUT_S', 0.3)
    with Dispatcher(config) as dispatcher:
        # The warm holder is a real subprocess worker over plane A...
        child = subprocess.Popen(
            [sys.executable, '-c', _WORKER_CHILD
             % (dispatcher.addr, plane_a)],
            cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        try:
            # ...that must advertise its digests + the piece map first.
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                stats = _stats(dispatcher.addr)
                if stats['cluster_cache']['piece_map'] \
                        and stats['cluster_cache']['directory_digests'] \
                        >= 15:
                    break
                time.sleep(0.2)
            else:
                raise AssertionError('holder never advertised: %r'
                                     % (stats['cluster_cache'],))
            # SIGKILL the holder: the directory still names it for a
            # staleness window, so the joiner's fetches hit a corpse.
            child.kill()
            child.wait(10)
            worker = Worker(dispatcher.addr,
                            cache_plane_dir=plane_b).start()
            try:
                ids = _consume(dispatcher.addr)
                diag = worker.diagnostics
            finally:
                worker.stop()
                worker.join()
        finally:
            if child.poll() is None:
                child.kill()
            child.wait(10)
    assert ids == list(range(ROWS))          # nothing lost
    assert diag['cache_peer_degraded'] > 0   # fetches failed, counted
    assert diag['cache_peer_fills'] == 0
    assert diag['cache_misses'] > 0          # ...and decode paid the bill
    assert shm_residue() == set()            # no leaked slabs/probes
    tmps = [n for n in os.listdir(plane_b) if n.startswith('.tmp.')]
    assert tmps == []                        # no half-published entries


_WORKER_CHILD = """\
import sys
sys.path.insert(0, %r)
from petastorm_tpu.service.worker import Worker
Worker(%%r, cache_plane_dir=%%r).run()
""" % REPO


def _stats(addr):
    import zmq

    from petastorm_tpu.service.worker import _Rpc
    context = zmq.Context()
    rpc = _Rpc(context, addr)
    try:
        return rpc.call({'op': 'stats'})
    finally:
        rpc.close()
        context.term()


def test_dispatcher_stats_cluster_rollup_shape(tmp_path, dataset):
    config = _config(dataset, str(tmp_path / 'p'))
    with Dispatcher(config) as dispatcher:
        stats = _stats(dispatcher.addr)
    rollup = stats['cluster_cache']
    assert set(rollup) == {'cache_remote_hits', 'cache_peer_fills',
                           'cache_peer_degraded', 'cache_affinity_routed',
                           'affinity_deferrals', 'directory_workers',
                           'directory_digests', 'piece_map'}


# -- fingerprint invariance satellite ----------------------------------------

def test_plane_context_invariant_to_non_semantic_knobs(tmp_path, dataset):
    """A scheduling / pool / transfer knob flip must not de-warm the
    fleet's cache: the plane context digests dataset bytes + decode
    identity (columns, predicate, transform) and NOTHING else.  PRs 6-9
    added scheduling=, transfer=, wire_dtypes= and autotune= — none may
    enter the key."""
    def context_of(**kwargs):
        with make_reader(dataset.url, num_epochs=1,
                         shuffle_row_groups=False, columnar_decode=True,
                         cache_type='plane',
                         cache_location=str(tmp_path / 'ctx'),
                         **kwargs) as reader:
            return reader._cache.plane.context

    base = context_of(workers_count=2, scheduling='fifo')
    assert context_of(workers_count=2, scheduling='adaptive') == base
    assert context_of(workers_count=5, scheduling='auto') == base
    assert context_of(workers_count=2, reader_pool_type='dummy') == base
    # ...and a SEMANTIC knob does re-key (control for the test itself).
    assert context_of(workers_count=2, schema_fields=['id']) != base


def test_spec_token_signature_carries_no_scheduling_knobs():
    """The spec_token surface is the decode identity and nothing else;
    a future kwarg like scheduling=/wire_dtypes= entering it would
    silently de-warm every fleet on a flag flip.  Signature pinned."""
    import inspect

    from petastorm_tpu.cache_plane import spec_token
    assert list(inspect.signature(spec_token).parameters) == [
        'schema_view', 'predicate', 'transform_spec']
