"""Non-local filesystem leg over fsspec's MemoryFileSystem (round-1 VERDICT
item #9) — the sandbox stand-in for GCS (the north star materializes datasets
to ``gs://`` for pod workers; gcsfs and MemoryFileSystem share the fsspec
``AbstractFileSystem`` surface: ``open``/``find``/``exists``/``rm``/listing,
no OS paths anywhere).

Covers the three load-bearing flows: writer (DatasetWriter + footer
metadata), reader (rows + columnar batches + sharding), and the pandas
converter cache including its GC (delete) path.
"""

import numpy as np
import pytest

from petastorm_tpu import make_batch_reader, make_reader
from tests.test_common import assert_rows_equal, create_test_dataset


@pytest.fixture()
def mem_dataset():
    import fsspec
    url = 'memory://ds_reader'
    ds = create_test_dataset(url, num_rows=20, rows_per_rowgroup=5)
    yield ds
    fsspec.filesystem('memory').rm('/ds_reader', recursive=True)


def test_writer_produces_footer_metadata_in_memory_fs(mem_dataset):
    import fsspec
    fs = fsspec.filesystem('memory')
    files = fs.find('/ds_reader')
    assert any(f.endswith('_common_metadata') for f in files)
    assert any(f.endswith('.parquet') for f in files)

    from petastorm_tpu.etl.dataset_metadata import get_schema_from_dataset_url
    schema = get_schema_from_dataset_url('memory://ds_reader')
    assert 'id' in schema.fields


def test_row_reader_over_memory_fs(mem_dataset):
    with make_reader('memory://ds_reader', reader_pool_type='thread',
                     workers_count=2, shuffle_row_groups=False) as reader:
        rows = [r._asdict() for r in reader]
    assert_rows_equal(rows, mem_dataset.data)


def test_batch_reader_over_memory_fs(mem_dataset):
    with make_batch_reader('memory://ds_reader', reader_pool_type='thread',
                           workers_count=2, shuffle_row_groups=False) as reader:
        total = sum(len(batch.id) for batch in reader)
    assert total == 20


def test_sharding_over_memory_fs(mem_dataset):
    seen = set()
    for shard in range(2):
        with make_reader('memory://ds_reader', cur_shard=shard, shard_count=2,
                         reader_pool_type='dummy') as reader:
            ids = {int(r.id) for r in reader}
        assert seen.isdisjoint(ids)
        seen |= ids
    assert seen == set(range(20))


def test_pandas_converter_cache_and_gc_over_memory_fs():
    import fsspec
    import pandas as pd
    from petastorm_tpu.spark import make_pandas_converter

    fs = fsspec.filesystem('memory')
    df = pd.DataFrame({'a': np.arange(10), 'b': np.arange(10) * 0.5})
    conv = make_pandas_converter(df, parent_cache_dir_url='memory://conv_cache')
    try:
        # Materialized under the cache dir; a second conversion of the same
        # frame dedups onto the same URL.
        assert conv.cache_dir_url.startswith('memory://')
        conv2 = make_pandas_converter(df, parent_cache_dir_url='memory://conv_cache')
        assert conv2.cache_dir_url == conv.cache_dir_url

        with make_batch_reader(conv.cache_dir_url, reader_pool_type='dummy') as reader:
            total = sum(len(b.a) for b in reader)
        assert total == 10
    finally:
        conv.delete()
    # GC removed the materialized files.
    leftover = [f for f in fs.find('/conv_cache')]
    assert not leftover, leftover
