"""Ring / all-to-all (Ulysses) sequence parallelism vs dense attention.

Runs on the 8-virtual-device CPU mesh (conftest) — the same shardings
compile unchanged on a TPU pod slice.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from petastorm_tpu.parallel import make_mesh
from petastorm_tpu.parallel.ring_attention import (
    full_attention, make_ring_attention, make_ulysses_attention)

B, S, H, D = 2, 64, 8, 16


@pytest.fixture(scope='module')
def qkv():
    rng = np.random.default_rng(7)
    mk = lambda: jnp.asarray(rng.standard_normal((B, S, H, D)), jnp.float32)
    return mk(), mk(), mk()


def _place(mesh, sharding, *arrays):
    return [jax.device_put(a, sharding) for a in arrays]


@pytest.mark.parametrize('causal', [False, True])
@pytest.mark.parametrize('mesh_axes', [{'seq': 8}, {'data': 2, 'seq': 4}])
def test_ring_matches_dense(qkv, causal, mesh_axes):
    mesh = make_mesh(mesh_axes)
    fn, sharding = make_ring_attention(mesh, causal=causal)
    q, k, v = _place(mesh, sharding, *qkv)
    got = jax.jit(fn)(q, k, v)
    want = full_attention(*qkv, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize('causal', [False, True])
def test_ulysses_matches_dense(qkv, causal):
    mesh = make_mesh({'seq': 8})
    fn, sharding = make_ulysses_attention(mesh, causal=causal)
    q, k, v = _place(mesh, sharding, *qkv)
    got = jax.jit(fn)(q, k, v)
    want = full_attention(*qkv, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_ring_gradients_match_dense(qkv):
    mesh = make_mesh({'seq': 8})
    fn, sharding = make_ring_attention(mesh, causal=True)
    q, k, v = qkv

    def loss_ring(q, k, v):
        return jnp.sum(fn(q, k, v) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(full_attention(q, k, v, causal=True) ** 2)

    got = jax.grad(loss_ring, argnums=(0, 1, 2))(*_place(mesh, sharding, q, k, v))
    want = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for g, w in zip(got, want):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=1e-4, atol=1e-4)


def test_ulysses_rejects_indivisible_heads(qkv):
    mesh = make_mesh({'seq': 8})
    fn, sharding = make_ulysses_attention(mesh)
    q, k, v = _place(mesh, sharding, *(x[:, :, :4] for x in qkv))  # 4 heads < 8 devices
    with pytest.raises(ValueError, match='not divisible'):
        jax.jit(fn)(q, k, v)


def test_ring_long_sequence_memory_shape(qkv):
    # 8× the sequence on the same mesh still only ever materialises
    # [seq_local, seq_local] score tiles; assert output correctness on a
    # longer-than-test default sequence as a smoke for the long-context path.
    rng = np.random.default_rng(11)
    s = 256
    mk = lambda: jnp.asarray(rng.standard_normal((1, s, 4, 8)), jnp.float32)
    q, k, v = mk(), mk(), mk()
    mesh = make_mesh({'seq': 8})
    fn, sharding = make_ring_attention(mesh, causal=True)
    got = jax.jit(fn)(*_place(mesh, sharding, q, k, v))
    want = full_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize('causal', [False, True])
@pytest.mark.parametrize('block_k', [4, 8, 3])  # 3: kv_local=8 pads to 9
def test_ring_chunked_matches_dense(qkv, causal, block_k):
    """block_k chunking (incl. non-divisible -> padded chunks) is exact."""
    mesh = make_mesh({'seq': 8})
    fn, sharding = make_ring_attention(mesh, causal=causal, block_k=block_k)
    q, k, v = _place(mesh, sharding, *qkv)
    got = jax.jit(fn)(q, k, v)
    want = full_attention(*qkv, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize('block_k', [4, 3])  # 3 exercises the masked tail
def test_ring_chunked_gradients_match_dense(qkv, block_k):
    mesh = make_mesh({'seq': 8})
    fn, sharding = make_ring_attention(mesh, causal=True, block_k=block_k)
    q, k, v = _place(mesh, sharding, *qkv)

    def loss_ring(q, k, v):
        return jnp.sum(fn(q, k, v) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(full_attention(q, k, v, causal=True) ** 2)

    got = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    want = jax.grad(loss_dense, argnums=(0, 1, 2))(*qkv)
    for g, w in zip(got, want):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=2e-3, atol=2e-3)


def test_ring_block_k_validated(qkv):
    mesh = make_mesh({'seq': 8})
    fn, sharding = make_ring_attention(mesh, block_k=0)
    q, k, v = _place(mesh, sharding, *qkv)
    with pytest.raises(ValueError, match='block_k'):
        jax.jit(fn)(q, k, v)


# -- packed (segment-restricted) sequence parallelism ------------------------

def _pack_segments(rng, b, s, max_segs=5):
    out = np.zeros((b, s), np.int32)
    for r in range(b):
        off = 0
        for seg in range(1, max_segs + 1):
            L = int(rng.integers(2, max(3, s // max_segs)))
            if off + L > s - 3:
                break
            out[r, off:off + L] = seg
            off += L
    return jnp.asarray(out)


@pytest.mark.parametrize('causal', [False, True])
@pytest.mark.parametrize('block_k', [None, 8])
def test_packed_ring_matches_packed_dense(qkv, causal, block_k):
    """Segment boundaries hold even when segments straddle ring shards."""
    rng = np.random.default_rng(11)
    seg = _pack_segments(rng, B, S)
    mesh = make_mesh({'seq': 8})
    fn, sharding = make_ring_attention(mesh, causal=causal, block_k=block_k,
                                       packed=True)
    q, k, v = _place(mesh, sharding, *qkv)
    seg_dev = jax.device_put(
        seg, jax.NamedSharding(mesh, P(None, 'seq')))
    got = jax.jit(fn)(q, k, v, seg_dev)
    want = full_attention(*qkv, causal=causal, segment_ids=seg)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize('causal', [False, True])
def test_packed_ulysses_matches_packed_dense(qkv, causal):
    rng = np.random.default_rng(12)
    seg = _pack_segments(rng, B, S)
    mesh = make_mesh({'seq': 8})
    fn, sharding = make_ulysses_attention(mesh, causal=causal, packed=True)
    q, k, v = _place(mesh, sharding, *qkv)
    seg_dev = jax.device_put(seg, jax.NamedSharding(mesh, P(None, 'seq')))
    got = jax.jit(fn)(q, k, v, seg_dev)
    want = full_attention(*qkv, causal=causal, segment_ids=seg)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_packed_ulysses_with_flash_attn_fn(qkv):
    from petastorm_tpu.ops import flash_attention
    rng = np.random.default_rng(13)
    seg = _pack_segments(rng, B, S)
    mesh = make_mesh({'seq': 8})
    fn, sharding = make_ulysses_attention(mesh, causal=True, packed=True,
                                          attn_fn=flash_attention)
    q, k, v = _place(mesh, sharding, *qkv)
    seg_dev = jax.device_put(seg, jax.NamedSharding(mesh, P(None, 'seq')))
    got = jax.jit(fn)(q, k, v, seg_dev)
    want = full_attention(*qkv, causal=True, segment_ids=seg)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_packed_ring_gradients_match_dense(qkv):
    rng = np.random.default_rng(14)
    seg = _pack_segments(rng, B, S)
    mesh = make_mesh({'seq': 8})
    fn, sharding = make_ring_attention(mesh, causal=True, packed=True)
    q, k, v = qkv

    def loss_ring(q, k, v):
        return (jax.jit(fn)(q, k, v, seg) ** 2).sum()

    def loss_dense(q, k, v):
        return (full_attention(q, k, v, causal=True,
                               segment_ids=seg) ** 2).sum()

    gr = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gr, gd, 'qkv'):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=3e-5, atol=3e-5,
                                   err_msg='d' + name)
