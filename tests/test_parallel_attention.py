"""Ring / all-to-all (Ulysses) sequence parallelism vs dense attention.

Runs on the 8-virtual-device CPU mesh (conftest) — the same shardings
compile unchanged on a TPU pod slice.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from petastorm_tpu.parallel import make_mesh
from petastorm_tpu.parallel.ring_attention import (
    full_attention, make_ring_attention, make_ulysses_attention)

B, S, H, D = 2, 64, 8, 16


@pytest.fixture(scope='module')
def qkv():
    rng = np.random.default_rng(7)
    mk = lambda: jnp.asarray(rng.standard_normal((B, S, H, D)), jnp.float32)
    return mk(), mk(), mk()


def _place(mesh, sharding, *arrays):
    return [jax.device_put(a, sharding) for a in arrays]


@pytest.mark.parametrize('causal', [False, True])
@pytest.mark.parametrize('mesh_axes', [{'seq': 8}, {'data': 2, 'seq': 4}])
def test_ring_matches_dense(qkv, causal, mesh_axes):
    mesh = make_mesh(mesh_axes)
    fn, sharding = make_ring_attention(mesh, causal=causal)
    q, k, v = _place(mesh, sharding, *qkv)
    got = jax.jit(fn)(q, k, v)
    want = full_attention(*qkv, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize('causal', [False, True])
def test_ulysses_matches_dense(qkv, causal):
    mesh = make_mesh({'seq': 8})
    fn, sharding = make_ulysses_attention(mesh, causal=causal)
    q, k, v = _place(mesh, sharding, *qkv)
    got = jax.jit(fn)(q, k, v)
    want = full_attention(*qkv, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_ring_gradients_match_dense(qkv):
    mesh = make_mesh({'seq': 8})
    fn, sharding = make_ring_attention(mesh, causal=True)
    q, k, v = qkv

    def loss_ring(q, k, v):
        return jnp.sum(fn(q, k, v) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(full_attention(q, k, v, causal=True) ** 2)

    got = jax.grad(loss_ring, argnums=(0, 1, 2))(*_place(mesh, sharding, q, k, v))
    want = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for g, w in zip(got, want):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=1e-4, atol=1e-4)


def test_ulysses_rejects_indivisible_heads(qkv):
    mesh = make_mesh({'seq': 8})
    fn, sharding = make_ulysses_attention(mesh)
    q, k, v = _place(mesh, sharding, *(x[:, :, :4] for x in qkv))  # 4 heads < 8 devices
    with pytest.raises(ValueError, match='not divisible'):
        jax.jit(fn)(q, k, v)


def test_ring_long_sequence_memory_shape(qkv):
    # 8× the sequence on the same mesh still only ever materialises
    # [seq_local, seq_local] score tiles; assert output correctness on a
    # longer-than-test default sequence as a smoke for the long-context path.
    rng = np.random.default_rng(11)
    s = 256
    mk = lambda: jnp.asarray(rng.standard_normal((1, s, 4, 8)), jnp.float32)
    q, k, v = mk(), mk(), mk()
    mesh = make_mesh({'seq': 8})
    fn, sharding = make_ring_attention(mesh, causal=True)
    got = jax.jit(fn)(*_place(mesh, sharding, q, k, v))
    want = full_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize('causal', [False, True])
@pytest.mark.parametrize('block_k', [4, 8, 3])  # 3: kv_local=8 pads to 9
def test_ring_chunked_matches_dense(qkv, causal, block_k):
    """block_k chunking (incl. non-divisible -> padded chunks) is exact."""
    mesh = make_mesh({'seq': 8})
    fn, sharding = make_ring_attention(mesh, causal=causal, block_k=block_k)
    q, k, v = _place(mesh, sharding, *qkv)
    got = jax.jit(fn)(q, k, v)
    want = full_attention(*qkv, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize('block_k', [4, 3])  # 3 exercises the masked tail
def test_ring_chunked_gradients_match_dense(qkv, block_k):
    mesh = make_mesh({'seq': 8})
    fn, sharding = make_ring_attention(mesh, causal=True, block_k=block_k)
    q, k, v = _place(mesh, sharding, *qkv)

    def loss_ring(q, k, v):
        return jnp.sum(fn(q, k, v) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(full_attention(q, k, v, causal=True) ** 2)

    got = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    want = jax.grad(loss_dense, argnums=(0, 1, 2))(*qkv)
    for g, w in zip(got, want):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=2e-3, atol=2e-3)


def test_ring_block_k_validated(qkv):
    mesh = make_mesh({'seq': 8})
    fn, sharding = make_ring_attention(mesh, block_k=0)
    q, k, v = _place(mesh, sharding, *qkv)
    with pytest.raises(ValueError, match='block_k'):
        jax.jit(fn)(q, k, v)
