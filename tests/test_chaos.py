"""The chaos plane (ISSUE 15): seam engine, seeded determinism, the
promoted fault-injection filesystems, the delivery digest, and one real
scenario through the matrix runner.

The full >= 6-scenario matrix runs via ``petastorm-tpu-chaos matrix``
(bench/CI); here the engine itself is pinned — a typo'd seam, a broken
budget, or a digest that stopped detecting duplicates would silently
hollow out every scenario's assertions.
"""

import json
import os
import pickle
import time

import fsspec
import numpy as np
import pytest

from petastorm_tpu.test_util import chaos


@pytest.fixture(autouse=True)
def _inert_chaos():
    """Every test starts and ends with no armed spec (the module global
    must never leak between tests)."""
    chaos.deactivate()
    yield
    chaos.deactivate()


# -- seam engine --------------------------------------------------------------

def test_inject_is_inert_without_activation():
    assert chaos.active() is None
    assert chaos.inject('worker.chunk', split=1, seq=0) is None


def test_budget_and_counts():
    state = chaos.activate({'seed': 0, 'faults': [
        {'seam': 'worker.chunk', 'action': 'drop', 'p': 1.0, 'max': 2}]})
    actions = [chaos.inject('worker.chunk', seq=i) for i in range(4)]
    assert actions == ['drop', 'drop', None, None]
    assert state.counts == {('worker.chunk', 'drop'): 2}
    assert state.fired() == 2


def test_ops_filter_matches_context():
    chaos.activate({'seed': 0, 'faults': [
        {'seam': 'rpc.request', 'action': 'drop', 'p': 1.0,
         'ops': ['heartbeat']}]})
    assert chaos.inject('rpc.request', op='lease') is None
    assert chaos.inject('rpc.request', op='heartbeat') == 'drop'


def test_seeded_decisions_are_deterministic():
    spec = {'seed': 42, 'faults': [
        {'seam': 'worker.chunk', 'action': 'drop', 'p': 0.5}]}
    runs = []
    for _ in range(2):
        chaos.activate(spec, salt=3)
        runs.append([chaos.inject('worker.chunk', seq=i)
                     for i in range(32)])
        chaos.deactivate()
    assert runs[0] == runs[1]
    assert 'drop' in runs[0] and None in runs[0]
    # A different salt (another process role) decorrelates the stream.
    chaos.activate(spec, salt=4)
    assert [chaos.inject('worker.chunk', seq=i)
            for i in range(32)] != runs[0]


def test_delay_action_sleeps_and_error_action_raises():
    chaos.activate({'seed': 0, 'faults': [
        {'seam': 'worker.decode', 'action': 'delay', 'p': 1.0,
         'delay_s': 0.05, 'max': 1},
        {'seam': 'fs.open', 'action': 'error', 'p': 1.0}]})
    t0 = time.monotonic()
    assert chaos.inject('worker.decode', split=0) == 'delay'
    assert time.monotonic() - t0 >= 0.05
    with pytest.raises(chaos.ChaosInjectedError):
        chaos.inject('fs.open', path='x.parquet')


def test_unknown_action_rejected_unknown_seam_warns():
    with pytest.raises(ValueError, match='action'):
        chaos.ChaosState({'faults': [{'seam': 'rpc.request',
                                      'action': 'explode'}]})
    # Unknown seam: tolerated (warn) — it can never fire.
    state = chaos.ChaosState({'faults': [{'seam': 'nope',
                                          'action': 'drop'}]})
    assert state.fire('rpc.request', {}) is None


def test_env_arming_round_trip(monkeypatch):
    spec = {'seed': 9, 'faults': [{'seam': 'worker.chunk',
                                   'action': 'dup', 'p': 1.0, 'max': 1}]}
    monkeypatch.setenv(chaos.CHAOS_ENV, json.dumps(spec))
    monkeypatch.setenv(chaos.CHAOS_SALT_ENV, '2')
    chaos._arm_from_env()
    assert chaos.inject('worker.chunk', seq=0) == 'dup'
    # Unparseable env must be ignored, never crash an importing worker.
    chaos.deactivate()
    monkeypatch.setenv(chaos.CHAOS_ENV, '{not json')
    chaos._arm_from_env()
    assert chaos.active() is None


# -- promoted fault-injection filesystems -------------------------------------

@pytest.fixture()
def parquet_file(tmp_path):
    import pyarrow as pa
    import pyarrow.parquet as pq
    path = str(tmp_path / 'part.parquet')
    pq.write_table(pa.table({'id': np.arange(8)}), path)
    return path


def test_is_data_file_rules():
    assert chaos.is_data_file('/x/part-0001.parquet')
    assert not chaos.is_data_file('/x/_common_metadata')
    assert not chaos.is_data_file('/x/_metadata.parquet')
    assert not chaos.is_data_file('/x/readme.txt')


def test_flaky_open_fails_then_recovers(parquet_file):
    fs = chaos.FlakyOpenFilesystem(fsspec.filesystem('file'),
                                   fail_times=2)
    for _ in range(2):
        with pytest.raises(OSError, match='injected transient open'):
            fs.open(parquet_file, 'rb')
    with fs.open(parquet_file, 'rb') as handle:
        assert handle.read(4) == b'PAR1'
    # Non-data files never fail.
    meta = str(os.path.dirname(parquet_file)) + '/_metadata'
    open(meta, 'wb').close()
    fs.open(meta, 'rb').close()


def test_flaky_read_dies_on_first_read_only(parquet_file):
    fs = chaos.FlakyReadFilesystem(fsspec.filesystem('file'),
                                   fail_times=1)
    handle = fs.open(parquet_file, 'rb')  # open SUCCEEDS...
    with pytest.raises(OSError, match='injected read failure'):
        handle.read(4)                    # ...the read dies
    with fs.open(parquet_file, 'rb') as second:
        assert second.read(4) == b'PAR1'


def test_flaky_fs_pickles_without_lock_or_counts(parquet_file):
    fs = chaos.FlakyOpenFilesystem(fsspec.filesystem('file'),
                                   fail_times=1)
    with pytest.raises(OSError):
        fs.open(parquet_file, 'rb')   # budget consumed in the parent
    clone = pickle.loads(pickle.dumps(fs))
    # The child re-arms: its injection budget is its own.
    with pytest.raises(OSError):
        clone.open(parquet_file, 'rb')
    with clone.open(parquet_file, 'rb') as handle:
        assert handle.read(4) == b'PAR1'


def test_fault_injection_back_compat_reexports():
    from petastorm_tpu.test_util import fault_injection
    assert fault_injection.FlakyOpenFilesystem \
        is chaos.FlakyOpenFilesystem
    assert fault_injection.FlakyReadFilesystem \
        is chaos.FlakyReadFilesystem
    assert fault_injection.is_data_file is chaos.is_data_file
    assert fault_injection._is_data_file is chaos.is_data_file


def test_bandwidth_limited_fs_registered_and_picklable(parquet_file):
    """The PR 14 emulation filesystem sits in the seam registry and —
    regression for the recursion bug the fetch_latency_spike scenario
    exposed — survives a pickle round trip (it rides reader_kwargs
    across the control plane)."""
    fs = chaos.FILESYSTEM_FAULTS['bandwidth_limited'](
        fsspec.filesystem('file'), bps=1e9)
    clone = pickle.loads(pickle.dumps(fs))
    with clone.open(parquet_file, 'rb') as handle:
        assert handle.read(4) == b'PAR1'


# -- delivery digest ----------------------------------------------------------

def test_delivery_digest_is_order_independent_and_content_exact():
    a = chaos.DeliveryDigest()
    a.update({'id': np.array([0, 1]), 'x': np.array([1.0, 2.0])})
    a.update({'id': np.array([2]), 'x': np.array([3.0])})
    b = chaos.DeliveryDigest()
    b.update({'id': np.array([2]), 'x': np.array([3.0])})
    b.update({'id': np.array([1, 0]), 'x': np.array([2.0, 1.0])})
    assert a.hexdigest() == b.hexdigest()
    assert a.rows == b.rows == 3
    # One flipped bit anywhere changes the digest...
    c = chaos.DeliveryDigest()
    c.update({'id': np.array([0, 1, 2]), 'x': np.array([1.0, 2.0, 3.1])})
    assert c.hexdigest() != a.hexdigest()
    # ...and a duplicated row can never cancel a missing one (the row
    # count rides in the digest).
    d = chaos.DeliveryDigest()
    d.update({'id': np.array([0, 0, 2]), 'x': np.array([1.0, 1.0, 3.0])})
    assert d.hexdigest() != a.hexdigest()


def test_direct_read_digest_matches_itself(tmp_path):
    url, rows = chaos.make_chaos_dataset(str(tmp_path / 'ds'), rows=16,
                                         payload_bytes=64)
    assert chaos.direct_read_digest(url) == chaos.direct_read_digest(url)
    assert rows == 16


# -- scenario catalogue + one real run ----------------------------------------

def test_scenario_catalogue_meets_the_acceptance_bar():
    # >= 6 distinct fault scenarios, covering every required class.
    assert len(chaos.SCENARIOS) >= 6
    for required in ('dispatcher_kill', 'worker_kill', 'worker_drain',
                     'message_drop', 'fetch_latency_spike',
                     'shm_enospc', 'plane_enospc',
                     # multi-tenant + autoscaler scenarios (ISSUE 16)
                     'autoscale_storm', 'autoscale_worker_kill',
                     'tenant_fair_share', 'tenant_worker_kill'):
        assert required in chaos.SCENARIOS, required
    assert set(chaos.SMOKE_SCENARIOS) <= set(chaos.SCENARIOS)
    # The CI smoke gained the scale-storm scenario (ISSUE 16).
    assert len(chaos.SMOKE_SCENARIOS) == 4
    assert 'autoscale_storm' in chaos.SMOKE_SCENARIOS
    for name, scenario in chaos.SCENARIOS.items():
        assert scenario.get('summary'), name
        for fault in scenario.get('faults') or ():
            assert fault['seam'] in chaos.SEAMS, (name, fault)


def test_message_drop_scenario_end_to_end(tmp_path):
    """One REAL scenario through the runner in-suite: dropped chunks and
    control RPCs, digest + exactly-once + zero residue asserted — the
    harness itself is what this pins (the full matrix runs in CI's
    chaos-smoke step and the bench)."""
    url, rows = chaos.make_chaos_dataset(str(tmp_path / 'ds'), seed=11)
    report = chaos.run_scenario('message_drop', url, rows,
                                str(tmp_path), seed=11)
    assert report['ok'], report
    assert report['checks']['digest'] == 'ok'
    assert report['checks']['exactly_once'] == 'ok'
    assert report['checks']['zero_residue'] == 'ok'
    assert sum(report['injections'].values()) > 0, \
        'scenario ran but injected nothing'


def test_error_action_restricted_to_handled_seams():
    """`action: error` is only accepted at seams whose caller models
    the failure — anywhere else the raise would kill the process (the
    dispatcher would die without sending its REP reply), which is an
    outage, not an injected fault."""
    with pytest.raises(ValueError, match='no\\s+handler'):
        chaos.ChaosState({'faults': [{'seam': 'dispatcher.rpc',
                                      'action': 'error'}]})
    with pytest.raises(ValueError, match='no\\s+handler'):
        chaos.ChaosState({'faults': [{'seam': 'worker.chunk',
                                      'action': 'error'}]})
    for seam in ('worker.decode', 'fs.open', 'fs.read'):
        chaos.ChaosState({'faults': [{'seam': seam, 'action': 'error'}]})
